"""Arrow Flight wire conformance.

The reference's JDBC driver speaks Flight directly: it opens a
FlightClient and sends the raw SQL bytes as a DoGet Ticket, then reads
the schema-first record-batch stream (reference:
jvm/jdbc/.../FlightStatement.java:44-63 — `new Ticket(sql.getBytes())`;
Driver.java:33-47 registers `jdbc:arrow://host:port`). These tests
replay exactly that byte exchange with a stock pyarrow FlightClient —
no ballista client code on the wire — proving any foreign Flight
client (the Java driver included) can talk to this server.
"""

import numpy as np
import pandas as pd
import pytest

paflight = pytest.importorskip("pyarrow.flight")
import pyarrow as pa  # noqa: E402

from ballista_tpu import Int64, Utf8, schema  # noqa: E402
from ballista_tpu.client import BallistaContext  # noqa: E402
from ballista_tpu.distributed.flight import serve_flight  # noqa: E402


@pytest.fixture()
def sql_server(tmp_path):
    from ballista_tpu.io import TblSource

    d = tmp_path / "t"
    d.mkdir()
    (d / "p0.tbl").write_text(
        "".join(f"k{i % 4}|{i}|\n" for i in range(200)))
    ctx = BallistaContext.standalone()
    ctx.register_source("t", TblSource(str(d), schema(("k", Utf8),
                                                      ("v", Int64))))

    def execute_sql(sql):
        return ctx.sql(sql).collect()

    server, port = serve_flight("127.0.0.1", 0, execute_sql=execute_sql)
    yield ctx, port
    server.shutdown()


def test_jdbc_driver_byte_exchange(sql_server):
    """The exact exchange FlightStatement.executeQuery performs: raw SQL
    bytes as the DoGet ticket, schema-first stream back."""
    ctx, port = sql_server
    client = paflight.connect(f"grpc://127.0.0.1:{port}")
    sql = "select k, sum(v) as sv from t group by k order by k"
    reader = client.do_get(paflight.Ticket(sql.encode("utf-8")))
    # schema arrives before any data, like the reference streams it
    assert reader.schema.names == ["k", "sv"]
    table = reader.read_all()
    got = table.to_pandas()
    exp = ctx.sql(sql).collect()
    np.testing.assert_array_equal(got["k"], exp["k"])
    np.testing.assert_array_equal(got["sv"].astype(np.int64),
                                  exp["sv"].astype(np.int64))


def test_get_flight_info_endpoint_echoes_command(sql_server):
    """Standard Flight discovery: GetFlightInfo(command) returns an
    endpoint whose ticket re-yields the query via DoGet."""
    ctx, port = sql_server
    client = paflight.connect(f"grpc://127.0.0.1:{port}")
    sql = b"select count(*) as n from t"
    info = client.get_flight_info(
        paflight.FlightDescriptor.for_command(sql))
    assert len(info.endpoints) == 1
    reader = client.do_get(info.endpoints[0].ticket)
    assert int(reader.read_all()["n"][0].as_py()) == 200


def test_fetch_partition_ticket(tmp_path):
    """A proto Action ticket streams a materialized partition file —
    the Flight-spoken twin of the raw data plane."""
    from ballista_tpu.columnar import ColumnBatch
    from ballista_tpu.distributed.dataplane import partition_path
    from ballista_tpu.io import ipc
    from ballista_tpu.proto import ballista_pb2 as pb

    s = schema(("a", Int64), ("name", Utf8))
    b = ColumnBatch.from_pydict(
        s, {"a": [1, 2, 3], "name": ["x", "y", "x"]})
    path = partition_path(str(tmp_path), "jobX", 2, 0)
    import os

    os.makedirs(os.path.dirname(path))
    ipc.write_partition(path, [b])

    server, port = serve_flight("127.0.0.1", 0, work_dir=str(tmp_path))
    try:
        client = paflight.connect(f"grpc://127.0.0.1:{port}")
        action = pb.Action()
        action.fetch_partition.job_id = "jobX"
        action.fetch_partition.stage_id = 2
        action.fetch_partition.partition_id = 0
        reader = client.do_get(
            paflight.Ticket(action.SerializeToString()))
        got = reader.read_all().to_pandas()
        assert list(got["a"]) == [1, 2, 3]
        assert list(got["name"]) == ["x", "y", "x"]
    finally:
        server.shutdown()


def test_sql_error_surfaces_as_flight_error(sql_server):
    ctx, port = sql_server
    client = paflight.connect(f"grpc://127.0.0.1:{port}")
    with pytest.raises(paflight.FlightError):
        client.do_get(
            paflight.Ticket(b"select nope from missing_table")).read_all()


def test_timestamp_ns_precision_preserved(sql_server):
    """to_timestamp results must not be truncated to day precision on
    the Flight wire (timestamps carry time-of-day)."""
    ctx, port = sql_server
    ctx.register_memtable(
        "tstab", schema(("s", Utf8)), {"s": ["2024-01-02T10:30:45"]})
    client = paflight.connect(f"grpc://127.0.0.1:{port}")
    reader = client.do_get(paflight.Ticket(
        b"select to_timestamp(s) as t from tstab"))
    got = reader.read_all().to_pandas()
    assert str(got["t"][0]) == "2024-01-02 10:30:45"
