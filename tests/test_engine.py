"""End-to-end single-process engine tests (logical plan -> results),
checked against independent pandas computations."""

import numpy as np
import pandas as pd
import pytest

from ballista_tpu import (
    schema, col, lit, sum_, avg, min_, max_, count,
    Int32, Int64, Decimal, Utf8, Date32, Float64,
)
from ballista_tpu.expr import SortExpr
from ballista_tpu.io import MemTableSource
from ballista_tpu.logical import LogicalPlanBuilder
from ballista_tpu.execution import collect


RNG = np.random.default_rng(42)
N = 5000


@pytest.fixture(scope="module")
def lineitem():
    s = schema(
        ("l_orderkey", Int64),
        ("l_quantity", Decimal(2)),
        ("l_extendedprice", Decimal(2)),
        ("l_discount", Decimal(2)),
        ("l_shipdate", Date32),
        ("l_returnflag", Utf8),
        ("l_linestatus", Utf8),
    )
    data = {
        "l_orderkey": RNG.integers(1, 1000, N),
        "l_quantity": RNG.integers(1, 51, N),
        "l_extendedprice": RNG.integers(100, 100000, N) / 100,
        "l_discount": RNG.integers(0, 11, N) / 100,
        "l_shipdate": RNG.integers(9000, 10000, N),
        "l_returnflag": RNG.choice(["A", "N", "R"], N),
        "l_linestatus": RNG.choice(["F", "O"], N),
    }
    src = MemTableSource.from_pydict(s, data, num_partitions=3)
    df = pd.DataFrame(data)
    return src, df


@pytest.fixture(scope="module")
def orders():
    s = schema(
        ("o_orderkey", Int64),
        ("o_custkey", Int64),
        ("o_orderdate", Date32),
    )
    data = {
        "o_orderkey": np.arange(1, 1000),
        "o_custkey": RNG.integers(1, 100, 999),
        "o_orderdate": RNG.integers(8900, 10100, 999),
    }
    return MemTableSource.from_pydict(s, data, num_partitions=2), pd.DataFrame(data)


def test_q1_style(lineitem):
    src, df = lineitem
    plan = (
        LogicalPlanBuilder.scan("lineitem", src)
        .filter(col("l_shipdate") <= lit(9700))
        .aggregate(
            [col("l_returnflag"), col("l_linestatus")],
            [
                sum_(col("l_quantity")).alias("sum_qty"),
                sum_(col("l_extendedprice") * (lit(1) - col("l_discount"))).alias("sum_disc"),
                avg(col("l_quantity")).alias("avg_qty"),
                count().alias("cnt"),
            ],
        )
        .sort([SortExpr(col("l_returnflag")), SortExpr(col("l_linestatus"))])
        .build()
    )
    got = collect(plan)

    d = df[df.l_shipdate <= 9700]
    exp = (
        d.groupby(["l_returnflag", "l_linestatus"])
        .apply(
            lambda g: pd.Series({
                "sum_qty": g.l_quantity.sum(),
                "sum_disc": (g.l_extendedprice * (1 - g.l_discount)).sum(),
                "avg_qty": g.l_quantity.mean(),
                "cnt": len(g),
            }),
            include_groups=False,
        )
        .reset_index()
        .sort_values(["l_returnflag", "l_linestatus"])
        .reset_index(drop=True)
    )
    assert list(got.columns) == ["l_returnflag", "l_linestatus", "sum_qty",
                                 "sum_disc", "avg_qty", "cnt"]
    np.testing.assert_array_equal(got.l_returnflag, exp.l_returnflag)
    np.testing.assert_array_equal(got.l_linestatus, exp.l_linestatus)
    np.testing.assert_allclose(got.sum_qty, exp.sum_qty, rtol=0)
    np.testing.assert_allclose(got.sum_disc, exp.sum_disc, rtol=1e-12)
    np.testing.assert_allclose(got.avg_qty, exp.avg_qty, atol=1e-6)
    np.testing.assert_array_equal(got.cnt, exp.cnt)


def test_ungrouped_aggregate(lineitem):
    src, df = lineitem
    plan = (
        LogicalPlanBuilder.scan("lineitem", src)
        .filter((col("l_discount") >= lit(0.03)) & (col("l_quantity") < lit(24)))
        .aggregate(
            [],
            [
                sum_(col("l_extendedprice") * col("l_discount")).alias("revenue"),
                count().alias("n"),
                min_(col("l_quantity")).alias("minq"),
                max_(col("l_quantity")).alias("maxq"),
            ],
        )
        .build()
    )
    got = collect(plan)
    d = df[(df.l_discount >= 0.03) & (df.l_quantity < 24)]
    assert len(got) == 1
    np.testing.assert_allclose(
        got.revenue[0], (d.l_extendedprice * d.l_discount).sum(), rtol=1e-12
    )
    assert got.n[0] == len(d)
    assert got.minq[0] == d.l_quantity.min()
    assert got.maxq[0] == d.l_quantity.max()


def test_join_fk(lineitem, orders):
    lsrc, ldf = lineitem
    osrc, odf = orders
    plan = (
        LogicalPlanBuilder.scan("orders", osrc)
        .join(
            LogicalPlanBuilder.scan("lineitem", lsrc),
            on=[("o_orderkey", "l_orderkey")],
        )
        .filter(col("o_orderdate") < lit(9500))
        .aggregate(
            [col("o_custkey")],
            [sum_(col("l_quantity")).alias("qty"), count().alias("n")],
        )
        .sort([SortExpr(col("o_custkey"))])
        .build()
    )
    got = collect(plan)
    j = ldf.merge(odf, left_on="l_orderkey", right_on="o_orderkey")
    j = j[j.o_orderdate < 9500]
    exp = (
        j.groupby("o_custkey")
        .agg(qty=("l_quantity", "sum"), n=("l_quantity", "size"))
        .reset_index()
        .sort_values("o_custkey")
        .reset_index(drop=True)
    )
    np.testing.assert_array_equal(got.o_custkey, exp.o_custkey)
    np.testing.assert_allclose(got.qty, exp.qty, rtol=0)
    np.testing.assert_array_equal(got.n, exp.n)


def test_sort_limit_projection(lineitem):
    src, df = lineitem
    plan = (
        LogicalPlanBuilder.scan("lineitem", src)
        .project([col("l_orderkey"), col("l_extendedprice")])
        .sort([SortExpr(col("l_extendedprice"), ascending=False),
               SortExpr(col("l_orderkey"))])
        .limit(10)
        .build()
    )
    got = collect(plan)
    exp = (
        df[["l_orderkey", "l_extendedprice"]]
        .sort_values(["l_extendedprice", "l_orderkey"], ascending=[False, True])
        .head(10)
        .reset_index(drop=True)
    )
    assert len(got) == 10
    np.testing.assert_array_equal(got.l_orderkey, exp.l_orderkey)
    np.testing.assert_allclose(got.l_extendedprice, exp.l_extendedprice)


def test_semi_anti_join(lineitem, orders):
    lsrc, ldf = lineitem
    osrc, odf = orders
    early = odf[odf.o_orderdate < 9000]
    for how in ("semi", "anti"):
        plan = (
            LogicalPlanBuilder.scan("lineitem", lsrc)
            .join(
                LogicalPlanBuilder.scan("orders", osrc)
                .filter(col("o_orderdate") < lit(9000)),
                on=[("l_orderkey", "o_orderkey")],
                how=how,
            )
            .aggregate([], [count().alias("n")])
            .build()
        )
        got = collect(plan)
        in_early = ldf.l_orderkey.isin(early.o_orderkey)
        exp_n = int(in_early.sum()) if how == "semi" else int((~in_early).sum())
        assert got.n[0] == exp_n, how
