"""Adaptive post-filter compaction: plan-level behavior pins.

A fused scan->filter pipeline compacts its output when few rows
survive (downstream operators then run at the survivors' capacity) and
backs off the per-batch live-count sync when the filter is
unselective (selectivity is stationary within a query).
"""

import numpy as np

from ballista_tpu import schema, col, lit, Int64
from ballista_tpu.io import MemTableSource
from ballista_tpu.physical.operators import FilterExec, ScanExec


def _scan(n, capacity=None):
    s = schema(("k", Int64), ("v", Int64))
    src = MemTableSource.from_pydict(
        s, {"k": np.arange(n), "v": np.arange(n)}, capacity=capacity)
    return ScanExec("t", src)


def test_selective_filter_compacts_output():
    from ballista_tpu.compile import bucket_capacity

    f = FilterExec(col("k") < lit(10), _scan(4096))
    batches = list(f.execute(0))
    assert len(batches) == 1
    b = batches[0]
    assert int(b.num_rows) == 10
    # capacity shrank to the survivors' canonical ladder rung (the
    # bucket floor by default), not the scan's 4096
    assert b.capacity == bucket_capacity(10)
    assert b.capacity < 4096
    assert sorted(np.asarray(b.column("k").values)[:10].tolist()) == \
        list(range(10))


def test_unselective_filter_keeps_capacity_and_backs_off():
    f = FilterExec(col("k") >= lit(0), _scan(4096))  # keeps everything
    b = next(iter(f.execute(0)))
    assert b.capacity == 4096
    assert int(b.num_rows) == 4096
    # two no-compact batches end the per-batch live-count sync
    list(f.execute(0))
    assert f._compact_misses >= 2
    list(f.execute(0))
    assert f._compact_misses == 2  # stopped counting: sync path skipped


def test_learned_floor_reuses_capacity():
    f = FilterExec(col("k") < lit(100), _scan(4096))
    b1 = next(iter(f.execute(0)))
    cap1 = b1.capacity
    # later executions (other partitions/runs) compact to the SAME rung
    b2 = next(iter(f.execute(0)))
    assert b2.capacity == cap1
    assert f._compact_floor == cap1
