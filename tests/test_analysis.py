"""Unified static-analysis engine tests (ballista_tpu/analysis/ +
dev/analyze.py) and regression pins for the real bugs its first run
surfaced.

Layout:
- fixture snippets per rule: one that trips, one clean, one suppressed,
  one baselined (the ISSUE 13 acceptance matrix);
- the tier-1 wiring: ONE ``dev/analyze.py --baseline
  dev/analysis_baseline.json`` subprocess over the whole package must
  exit 0 inside the 10s runtime budget (this replaces N per-lint
  shells; the old ``dev/check_*.py`` entry points stay as shims and
  keep their own tests);
- regression tests for the fixes: cancel checks in the parquet/text
  scan chunk loops, the dataplane fetch loops and the IPC decode/
  assembly paths, plus ``device.block`` spans on the shuffle-write and
  result-materialization syncs.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from ballista_tpu import Int64, Utf8, schema
from ballista_tpu import analysis
from ballista_tpu.columnar import ColumnBatch
from ballista_tpu.errors import QueryCancelled
from ballista_tpu.io import ipc
from ballista_tpu.lifecycle import CancelToken, bind_token
from ballista_tpu.observability import tracing

REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))
ANALYZE = os.path.join(REPO, "dev", "analyze.py")


def _pkg(tmp_path, files):
    root = tmp_path / "fixroot"
    for rel, text in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return analysis.Package.load(str(root), package_rel="fixpkg")


def _run(pkg, rule, baseline=None):
    return analysis.analyze(pkg, [rule], baseline)


# ---------------------------------------------------------------------------
# engine mechanics: suppression, baseline, stale entries
# ---------------------------------------------------------------------------


def test_suppression_and_baseline_mechanics(tmp_path):
    trip = """
        import jax

        def f(x):
            return jax.device_get(x)
    """
    suppressed = """
        import jax

        def f(x):
            return jax.device_get(x)  # ballista: ignore[sync-span] host scalar
    """
    pkg = _pkg(tmp_path, {"fixpkg/trip.py": trip,
                          "fixpkg/ok.py": suppressed})
    rule = analysis.RULE_FACTORIES["sync-span"]()
    res = _run(pkg, rule)
    assert [f.file for f in res.findings] == ["fixpkg/trip.py"]
    assert res.suppressed == 1

    # baselined: the same finding matched by (rule, file, anchor)
    f = res.findings[0]
    bl = analysis.Baseline([{"rule": f.rule, "file": f.file,
                             "anchor": f.anchor, "note": "fixture"}])
    res2 = _run(pkg, rule, bl)
    assert res2.findings == [] and len(res2.baselined) == 1
    assert res2.ok

    # a stale entry (site fixed/moved away) is reported, not fatal
    bl2 = analysis.Baseline([{"rule": f.rule, "file": f.file,
                              "anchor": "gone_anchor()", "note": "old"}])
    res3 = _run(pkg, rule, bl2)
    assert len(res3.stale) == 1 and not res3.ok  # finding unbaselined


def test_comment_only_suppression_covers_next_line(tmp_path):
    src = """
        import jax

        def f(x):
            # ballista: ignore[sync-span] resolved scalars only
            return jax.device_get(x)
    """
    pkg = _pkg(tmp_path, {"fixpkg/m.py": src})
    res = _run(pkg, analysis.RULE_FACTORIES["sync-span"]())
    assert res.findings == [] and res.suppressed == 1


# ---------------------------------------------------------------------------
# cancel-coverage fixtures
# ---------------------------------------------------------------------------


def test_cancel_coverage_trips_and_clean(tmp_path):
    src = """
        from .lc import check_cancel

        def bad(plan, parts):
            out = []
            for batch in plan.execute(parts):
                out.append(decode(batch))
            return out

        def good(plan, parts):
            out = []
            for batch in plan.execute(parts):
                check_cancel()
                out.append(decode(batch))
            return out

        def metadata_only(locs):
            seen = {}
            for part in locs:
                seen[part.stage] = part.rows
            return seen
    """
    pkg = _pkg(tmp_path, {"fixpkg/mod.py": src,
                          "fixpkg/lc.py": "def check_cancel():\n    pass\n"})
    from ballista_tpu.analysis.passes.cancel_coverage import (
        CancelCoverageRule,
    )

    rule = CancelCoverageRule(critical_modules={"fixpkg/mod.py"})
    res = _run(pkg, rule)
    assert len(res.findings) == 1
    assert "bad" in res.findings[0].message


def test_cancel_coverage_follows_one_call_level(tmp_path):
    src = """
        from .lc import check_cancel

        def _pump(x):
            check_cancel()
            return x

        def covered(stream):
            for chunk in stream:
                _pump(chunk)

        class Reader:
            def _bail(self):
                check_cancel()

            def covered_method(self, stream):
                for chunk in stream:
                    self._bail()
                    use(chunk)
    """
    pkg = _pkg(tmp_path, {"fixpkg/mod.py": src,
                          "fixpkg/lc.py": "def check_cancel():\n    pass\n"})
    from ballista_tpu.analysis.passes.cancel_coverage import (
        CancelCoverageRule,
    )

    rule = CancelCoverageRule(critical_modules={"fixpkg/mod.py"})
    assert _run(pkg, rule).findings == []


def test_cancel_coverage_satisfiers_are_receiver_gated(tmp_path):
    """An unrelated validator.check(b) or future-style .cancelled probe
    must NOT satisfy the rule; token-ish receivers must."""
    src = """
        def bad(batches, validator):
            for b in batches:
                validator.check(b)
                process(b)

        def bad2(batches, fut):
            for b in batches:
                if fut.cancelled():
                    break
                process(b)

        def ok(batches, token):
            for b in batches:
                token.check()
                process(b)

        def ok2(batches, cancel_token):
            for b in batches:
                if cancel_token.cancelled:
                    break
                process(b)
    """
    pkg = _pkg(tmp_path, {"fixpkg/mod.py": src})
    from ballista_tpu.analysis.passes.cancel_coverage import (
        CancelCoverageRule,
    )

    rule = CancelCoverageRule(critical_modules={"fixpkg/mod.py"})
    found = {f.message.split(" ")[3] for f in _run(pkg, rule).findings}
    assert found == {"bad", "bad2"}, found


# ---------------------------------------------------------------------------
# sync-span fixtures
# ---------------------------------------------------------------------------


def test_sync_span_matrix(tmp_path):
    src = """
        import jax
        import numpy as np
        from .tr import trace_span

        def bad(col):
            return np.asarray(col.values)

        def bad2(x):
            return jax.device_get(x)

        def spanned(col):
            with trace_span("device.block", site="fix"):
                return np.asarray(col.selection)

        def host_object(d):
            return np.asarray(d.values, dtype=object)

        def host_input(rows):
            return np.asarray([r for r in rows])

        def provenance(b):
            import jax.numpy as jnp
            y = jnp.sum(b)
            return np.asarray(y)
    """
    pkg = _pkg(tmp_path, {
        "fixpkg/mod.py": src,
        "fixpkg/tr.py": ("from contextlib import contextmanager\n"
                         "@contextmanager\n"
                         "def trace_span(name, **kw):\n    yield\n"),
    })
    res = _run(pkg, analysis.RULE_FACTORIES["sync-span"]())
    lines = sorted(f.line for f in res.findings)
    msgs = " | ".join(f.message for f in res.findings)
    assert len(res.findings) == 3, msgs
    assert "np.asarray on a device value" in msgs
    assert "device_get" in msgs
    # spanned / dtype=object / host-list sites are NOT findings
    assert all(f.file == "fixpkg/mod.py" for f in res.findings)
    assert lines == sorted(lines)


def test_h2d_discipline_matrix(tmp_path):
    """Scan-source uploads must sit behind serve_or_fill: direct
    uploads in scan(), or in a module that never routes through the
    residency layer, are findings; produce-callback uploads and
    non-scan modules (shuffle codecs) are not."""
    unrouted = """
        from ..columnar import ColumnBatch

        class RogueSource:
            def scan(self, partition):
                yield from self._parts[partition]

            @classmethod
            def from_data(cls, schema, data):
                return [ColumnBatch.from_numpy(schema, data, {}, 8)]
    """
    routed = """
        import jax.numpy as jnp
        from ..columnar import ColumnBatch
        from ..cache.residency import serve_or_fill

        class GoodSource:
            def scan(self, partition):
                yield from serve_or_fill(
                    self._key(partition),
                    lambda: self._scan_direct(partition))

            def _scan_direct(self, partition):
                yield ColumnBatch.from_numpy(
                    self._schema, self._arrays[partition], {}, 8)

        class FrontRunner:
            def scan(self, partition):
                for arr in self._arrays[partition]:
                    yield jnp.asarray(arr)  # upload BEFORE the layer
    """
    codec = """
        import jax.numpy as jnp

        def decode(vals):
            return jnp.asarray(vals)  # shuffle wire codec: no scan
    """
    pkg = _pkg(tmp_path, {
        "fixpkg/io/unrouted.py": unrouted,
        "fixpkg/io/routed.py": routed,
        "fixpkg/io/codec.py": codec,
    })
    res = _run(pkg, analysis.RULE_FACTORIES["h2d-discipline"]())
    by_file = {}
    for f in res.findings:
        by_file.setdefault(f.file, []).append(f.message)
    assert list(by_file.get("fixpkg/io/unrouted.py", [])), by_file
    assert "never routes through" in by_file["fixpkg/io/unrouted.py"][0]
    assert len(by_file.get("fixpkg/io/routed.py", [])) == 1, by_file
    assert "in front of the residency layer" in \
        by_file["fixpkg/io/routed.py"][0]
    assert "fixpkg/io/codec.py" not in by_file


def test_h2d_discipline_real_tree_clean():
    """The live io/ sources hold the discipline (memory.py's
    registration-time upload is the one triaged baseline entry)."""
    pkg = analysis.Package.load(REPO)
    res = _run(pkg, analysis.RULE_FACTORIES["h2d-discipline"]())
    files = sorted({f.file for f in res.findings})
    assert files == ["ballista_tpu/io/memory.py"], files


# ---------------------------------------------------------------------------
# lock-discipline fixtures
# ---------------------------------------------------------------------------


def test_lock_discipline_matrix(tmp_path):
    src = """
        import threading

        _lock = threading.Lock()
        _cache = {}
        _safe = {}

        def bad_write(k, v):
            _cache[k] = v

        def good_write(k, v):
            with _lock:
                _safe[k] = v

        def _fill_locked(k, v):
            _cache[k] = v

        def dcl(key, locks):
            if key not in _cache:
                with _lock:
                    if key not in _cache:
                        _cache[key] = 1
            return _cache[key]

        def keyed(key, key_locks):
            if key not in _cache:
                with key_locks.get(key):
                    if key not in _cache:
                        with _lock:
                            _cache[key] = 1
            return _cache[key]
    """
    pkg = _pkg(tmp_path, {"fixpkg/mod.py": src})
    res = _run(pkg, analysis.RULE_FACTORIES["lock-discipline"]())
    by_msg = {}
    for f in res.findings:
        kind = ("dcl" if "double-checked" in f.message else "write")
        by_msg.setdefault(kind, []).append(f.line)
    # exactly one unguarded write (bad_write; *_locked exempt, dcl's
    # write is under the lock) and one hand-rolled DCL (keyed() uses
    # the KeyedLocks carrier and is exempt)
    assert len(by_msg.get("write", [])) == 1, res.findings
    assert len(by_msg.get("dcl", [])) == 1, res.findings


# ---------------------------------------------------------------------------
# migrated code-shape lints: seeded-violation parity
# ---------------------------------------------------------------------------


def test_jit_and_dict_rules_fire_on_seeded_violations(tmp_path):
    src = """
        import jax
        import numpy as np

        def rogue(xs, dicts):
            f = jax.jit(lambda x: x + 1)
            u = np.unique(np.concatenate(dicts))
            return f(xs), u

        def opted_out(xs, dicts):
            f = jax.jit(lambda x: x)  # jit-ok: fixture
            u = np.searchsorted(dicts, xs)  # dict-ok: fixture
            return f, u
    """
    pkg = _pkg(tmp_path, {"fixpkg/mod.py": src})
    jit = _run(pkg, analysis.RULE_FACTORIES["jit-sites"]()).findings
    dct = _run(pkg, analysis.RULE_FACTORIES["dict-sites"]()).findings
    assert len(jit) == 1 and len(dct) == 1


def test_metric_and_fault_rules_fire_on_seeded_violations(tmp_path):
    src = """
        def record(m):
            m.add_counter("bogus_metric_xyz")
            fault_point("bogus.point.xyz")
    """
    pkg = _pkg(tmp_path, {"fixpkg/mod.py": src})
    metric = _run(pkg, analysis.RULE_FACTORIES["metric-names"]()).findings
    fault = _run(pkg, analysis.RULE_FACTORIES["fault-points"]()).findings
    assert any("bogus_metric_xyz" in f.message for f in metric)
    assert any("bogus.point.xyz" in f.message for f in fault)


# ---------------------------------------------------------------------------
# the tier-1 wiring: whole-package run, runtime budget, CLI modes
# ---------------------------------------------------------------------------


def test_whole_package_analysis_clean_within_budget():
    """dev/analyze.py runs every pass over ballista_tpu/ in ONE process,
    exits 0 with the committed baseline, inside the 10s budget."""
    t0 = time.perf_counter()
    r = subprocess.run(
        [sys.executable, ANALYZE,
         "--baseline", os.path.join("dev", "analysis_baseline.json")],
        capture_output=True, text=True, cwd=REPO, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    elapsed = time.perf_counter() - t0
    assert r.returncode == 0, r.stdout + r.stderr
    assert "analysis clean" in r.stdout
    assert elapsed < 10.0, f"analysis took {elapsed:.1f}s (budget 10s)"


def test_analyze_json_and_changed_only_modes():
    r = subprocess.run(
        [sys.executable, ANALYZE, "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    assert payload["findings"] == []
    assert payload["baselined"] > 0 and payload["suppressed"] > 0

    r2 = subprocess.run(
        [sys.executable, ANALYZE, "--changed-only"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr


def test_analyze_exits_nonzero_on_new_finding(tmp_path):
    """A seeded violation in a staged tree fails the driver (and the
    engine loads standalone — no ballista_tpu/__init__ needed)."""
    import shutil

    stage = tmp_path / "repo"
    (stage / "dev").mkdir(parents=True)
    pkg = stage / "ballista_tpu"
    pkg.mkdir()
    (pkg / "rogue.py").write_text(
        "import numpy as np\n"
        "def unify(dicts):\n"
        "    return np.unique(np.concatenate(dicts))\n")
    shutil.copy(ANALYZE, stage / "dev" / "analyze.py")
    shutil.copytree(os.path.join(REPO, "ballista_tpu", "analysis"),
                    pkg / "analysis",
                    ignore=shutil.ignore_patterns("__pycache__"))
    r = subprocess.run(
        [sys.executable, str(stage / "dev" / "analyze.py"),
         "--rules", "dict-sites"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 1 and "rogue.py" in r.stderr


# ---------------------------------------------------------------------------
# regression pins for the bugs the first whole-package run surfaced
# ---------------------------------------------------------------------------


def _mkbatch(n=512):
    s = schema(("a", Int64), ("k", Utf8))
    return s, ColumnBatch.from_pydict(s, {
        "a": list(range(n)),
        "k": [f"v{i % 7}" for i in range(n)],
    })


def test_parquet_scan_checks_cancel(tmp_path):
    """io/parquet.py: the batch-emit chunk loop stops at the next
    boundary once the thread's token fires (found by cancel-coverage —
    the loop had no check before ISSUE 13)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"a": list(range(4000))}), path)
    from ballista_tpu.io.parquet import ParquetSource

    src = ParquetSource(path, batch_capacity=1024)
    token = CancelToken()
    with bind_token(token):
        it = src.scan(0)
        next(it)  # first chunk emits fine
        token.cancel("test")
        with pytest.raises(QueryCancelled):
            next(it)


def test_text_scan_checks_cancel(tmp_path):
    """io/text.py: same boundary, same bug class, text path."""
    from ballista_tpu.io.text import DelimitedSource

    path = str(tmp_path / "t.tbl")
    with open(path, "w") as fh:
        for i in range(4000):
            fh.write(f"{i}|x{i % 5}|\n")
    s = schema(("a", Int64), ("k", Utf8))
    src = DelimitedSource(str(tmp_path), s, "|", trailing_delimiter=True,
                          batch_capacity=1024)
    token = CancelToken()
    with bind_token(token):
        it = src.scan(0)
        next(it)
        token.cancel("test")
        with pytest.raises(QueryCancelled):
            next(it)


def test_ipc_batch_iter_checks_cancel(tmp_path):
    """io/ipc.py: a fired token aborts a partition decode even through
    the shared record-batch iterator (not just the chunk-fed path
    test_spill already pins)."""
    _, b = _mkbatch(2048)
    path = str(tmp_path / "p" / "data.arrow")
    w = ipc.PartitionWriter(path, chunk_bytes=2048)
    w.write_batch(b)
    w.close()
    token = CancelToken()
    token.cancel("test")
    with bind_token(token):
        with pytest.raises(QueryCancelled):
            ipc.read_partition_arrays(path)


def test_batches_from_parts_checks_cancel(tmp_path):
    """io/ipc.py: shuffle-read assembly (pad + H2D per part) stops
    between parts once cancelled."""
    s, b = _mkbatch(64)
    path = str(tmp_path / "p" / "data.arrow")
    ipc.write_partition(path, [b])
    _, arrays, nulls, dicts, _ = ipc.read_partition_arrays(path)
    token = CancelToken()
    token.cancel("test")
    with bind_token(token):
        with pytest.raises(QueryCancelled):
            ipc.batches_from_parts(s, [(arrays, nulls, dicts)])


def test_dataplane_fetch_checks_cancel(tmp_path):
    """distributed/dataplane.py: a fired token aborts a chunk-stream
    fetch mid-transfer on BOTH framings (streaming and legacy)."""
    from ballista_tpu.distributed import dataplane

    _, b = _mkbatch(2048)
    wd = str(tmp_path / "wd")
    path = dataplane.partition_path(wd, "job1", 1, 0)
    ipc.write_partition(path, [b])
    for stream_serve in (True, False):
        server = dataplane.DataPlaneServer("localhost", 0, wd)
        server.stream_serve = stream_serve
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            token = CancelToken()
            with bind_token(token):
                it = dataplane.fetch_partition_chunks(
                    "localhost", server.port, "job1", 1, 0,
                    chunk_bytes=1024, window_bytes=2048)
                next(it)  # stream is live
                token.cancel("test")
                with pytest.raises(QueryCancelled):
                    for _chunk in it:
                        pass
        finally:
            server.close()


def test_shuffle_write_sync_is_spanned():
    """io/ipc.py batch_to_arrow: the D2H compaction fetch now runs
    under ONE device.block span (found by sync-span — the shuffle-write
    path synced with zero spans before ISSUE 13), so the profiler's
    device_blocked lane sees shuffle-write sync time."""
    tracing.reconfigure()
    assert tracing.flight_recorder_enabled()
    _, b = _mkbatch(256)
    since = time.time() - 0.5
    ipc.batch_to_arrow(b)
    spans = [r for r in tracing.ring_records(since=since)
             if r.get("name") == "device.block"
             and r.get("site") == "ipc.batch_to_arrow"]
    assert spans, "batch_to_arrow emitted no device.block span"


def test_column_to_numpy_sync_is_spanned():
    """columnar.py to_numpy_logical: result materialization D2H runs
    under a device.block span."""
    tracing.reconfigure()
    _, b = _mkbatch(64)
    since = time.time() - 0.5
    b.columns[0].to_numpy_logical()
    spans = [r for r in tracing.ring_records(since=since)
             if r.get("name") == "device.block"
             and r.get("site") == "column.to_numpy"]
    assert spans, "to_numpy_logical emitted no device.block span"


def test_set_process_identity_first_writer_wins_under_lock():
    """observability/tracing.py: concurrent identity claims settle to
    exactly one role (lock-discipline fix; was a check-then-write race
    on the module-level dict)."""
    saved = dict(tracing._identity)
    tracing._identity.clear()
    try:
        roles = ["executor", "scheduler"] * 8
        threads = [threading.Thread(target=tracing.set_process_identity,
                                    args=(r, f"e{i}"))
                   for i, r in enumerate(roles)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        ident = tracing.process_identity()
        assert ident.get("role") in ("executor", "scheduler")
        assert ident.get("exec", "").startswith("e")
    finally:
        tracing._identity.clear()
        tracing._identity.update(saved)
