"""Query profiler artifacts + live cluster health plane (ISSUE 5).

Covers: Chrome-trace artifact schema + loadability, BALLISTA_PROFILE
ambient profiling, structural span ids / flow correlation, trace-file
hygiene knobs, /healthz + Prometheus /metrics + /debug/queries on the
scheduler and executors (heartbeat resource gauges aggregated), the
slow-query log, memory-accounting monotonicity, the metric-name lint,
and the enabled-vs-disabled overhead gate (drift-cancelling
measurement, same scheme as PR 1's metrics gate)."""

import json
import os
import re
import subprocess
import sys
import time

import pytest

from ballista_tpu.client import BallistaContext
from ballista_tpu.datatypes import Float64, Int64, Utf8, schema
from ballista_tpu.observability import memory as obs_memory
from ballista_tpu.observability import tracing as obs_tracing
from ballista_tpu.observability.export import LANE_NAMES
from ballista_tpu.observability.health import render_prometheus

REPO = os.path.join(os.path.dirname(__file__), "..")


@pytest.fixture
def ctx():
    c = BallistaContext.standalone()
    c.register_memtable(
        "t", schema(("k", Utf8), ("a", Int64), ("b", Float64)),
        {"k": ["x", "y", "z"] * 20,
         "a": list(range(60)),
         "b": [float(i) / 4 for i in range(60)]},
    )
    c.register_memtable(
        "u", schema(("k", Utf8), ("w", Int64)),
        {"k": ["x", "y", "z"], "w": [7, 11, 13]},
    )
    return c


@pytest.fixture
def clean_env():
    keys = ("BALLISTA_TRACE", "BALLISTA_TRACE_FILE", "BALLISTA_TRACE_DIR",
            "BALLISTA_TRACE_TRUNCATE", "BALLISTA_TRACE_MAX_MB",
            "BALLISTA_PROFILE", "BALLISTA_SLOW_QUERY_SECS",
            "BALLISTA_METRICS_PORT")
    saved = {k: os.environ.get(k) for k in keys}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    obs_tracing.reconfigure()


# ---------------------------------------------------------------------------
# (a) profile artifact: Chrome trace schema, lanes, loadability
# ---------------------------------------------------------------------------


_VALID_PH = {"X", "i", "M", "s", "f"}


def _validate_chrome_trace(art: dict) -> None:
    """Pin the Chrome trace event schema the artifact promises: what
    chrome://tracing / Perfetto actually require of each event."""
    events = art["traceEvents"]
    assert isinstance(events, list) and events, "no trace events"
    for ev in events:
        assert ev["ph"] in _VALID_PH, ev
        assert isinstance(ev["name"], str) and ev["name"], ev
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        elif ev["ph"] == "i":
            assert ev.get("s") in ("t", "p", "g")
        elif ev["ph"] == "M":
            assert ev["name"] in ("process_name", "thread_name",
                                  "process_sort_index")
            assert ev["args"], ev
        elif ev["ph"] in ("s", "f"):
            # flow arrows: must carry an id and bind to a timestamp
            assert "id" in ev and isinstance(ev["ts"], (int, float))
    assert art.get("displayTimeUnit") in ("ms", "ns")


def test_profile_artifact_schema_and_lanes(ctx, clean_env, tmp_path):
    df = ctx.sql(
        "SELECT t.k, sum(t.a) AS s, sum(u.w) AS ws FROM t "
        "JOIN u ON t.k = u.k WHERE t.a > 0 GROUP BY t.k ORDER BY t.k"
    )
    path = df.profile(path=str(tmp_path / "art.json"), label="join-agg")
    art = json.load(open(path))

    assert art["schema"] == "ballista-profile-v1"
    assert art["label"] == "join-agg"
    assert art["wall_seconds"] > 0
    _validate_chrome_trace(art)

    # the six named lanes exist, partition the wall clock (remainder
    # included), and the coverage metric is the honest measured share
    assert set(art["lanes"]) == set(LANE_NAMES)
    assert all(v >= 0 for v in art["lanes"].values())
    wall = art["wall_seconds"]
    covered = (min(art["measured_seconds"], wall)
               + art["lanes"]["xla_execute_other"])
    assert abs(covered - wall) <= wall * 0.01 + 1e-6, art["lanes"]
    assert 0.0 <= art["attributed_fraction"] <= 1.0
    # this query compiles several kernels cold: the measured lanes must
    # hold real time, not all-zeros-plus-remainder
    assert art["lanes"]["compile_trace_lower"] > 0
    # per-operator metrics merged into the same artifact
    ops = art["operators"]
    assert ops and any("HashAggregateExec" in r["operator"] for r in ops)
    assert any(r["metrics"].get("output_rows", 0) > 0 for r in ops)
    # memory plane snapshot rides along
    mem = art["memory"]
    assert mem["rss_bytes"] > 0 and "by_category" in mem
    # artifact loads end-to-end: a fresh json round-trip is identical
    assert json.loads(json.dumps(art)) == art


def test_profile_env_dir_writes_artifact(ctx, clean_env, tmp_path):
    out_dir = tmp_path / "profiles"
    os.environ["BALLISTA_PROFILE"] = str(out_dir)
    try:
        ctx.sql("SELECT k, sum(a) AS s FROM t GROUP BY k").collect()
    finally:
        os.environ.pop("BALLISTA_PROFILE", None)
    files = list(out_dir.glob("ballista-profile-*.json"))
    assert len(files) == 1
    art = json.load(open(files[0]))
    _validate_chrome_trace(art)
    assert 0.0 <= art["attributed_fraction"] <= 1.0


# ---------------------------------------------------------------------------
# (b) structural span ids + flow correlation + trace hygiene
# ---------------------------------------------------------------------------


@pytest.fixture
def trace_file(clean_env, tmp_path):
    path = str(tmp_path / "trace.jsonl")
    os.environ["BALLISTA_TRACE"] = "1"
    os.environ["BALLISTA_TRACE_FILE"] = path
    obs_tracing.reconfigure()
    yield path


def test_span_ids_parents_and_flow(trace_file):
    from ballista_tpu.observability import flow, trace_event, trace_span

    with flow(job="j1", stage=2):
        with trace_span("outer.span", task="t0"):
            trace_event("inner.event", detail="x")
            with trace_span("inner.span"):
                pass
    recs = {r["name"]: r for r in
            (json.loads(ln) for ln in open(trace_file))}
    outer, inner = recs["outer.span"], recs["inner.span"]
    ev = recs["inner.event"]
    # span ids are unique, parents structural (not timestamp guesses)
    assert outer["sid"] != inner["sid"]
    assert inner["psid"] == outer["sid"]
    assert ev["psid"] == outer["sid"] and "sid" not in ev
    assert "psid" not in outer
    # flow attrs inherited by every record under the binding
    for r in (outer, inner, ev):
        assert r["job"] == "j1" and r["stage"] == 2
    # explicit span attrs win over nothing-lost
    assert outer["task"] == "t0"


def test_prefetch_producer_inherits_flow(trace_file):
    from ballista_tpu.ingest import PrefetchHandle
    from ballista_tpu.observability import flow

    with flow(job="jf", task="jf/0/0"):
        h = PrefetchHandle(lambda: iter([1, 2]), depth=2, label="scan")
    assert list(h) == [1, 2]
    recs = [json.loads(ln) for ln in open(trace_file)]
    pref = [r for r in recs if r["name"] == "ingest.prefetch"]
    assert pref and pref[0].get("job") == "jf", pref


def test_trace_truncate_and_size_cap(clean_env, tmp_path):
    path = str(tmp_path / "t.jsonl")
    open(path, "w").write('{"name": "stale.old_run"}\n' * 100)
    os.environ["BALLISTA_TRACE"] = "1"
    os.environ["BALLISTA_TRACE_FILE"] = path
    os.environ["BALLISTA_TRACE_TRUNCATE"] = "1"
    os.environ["BALLISTA_TRACE_MAX_MB"] = "0.001"  # 1000 bytes
    obs_tracing.reconfigure()
    from ballista_tpu.observability import trace_event

    for i in range(200):
        trace_event("hygiene.spam", i=i, pad="y" * 50)
    obs_tracing.reconfigure()  # flush/close
    lines = [json.loads(ln) for ln in open(path)]
    names = [r["name"] for r in lines]
    assert "stale.old_run" not in names  # truncated on open
    assert names[-1] == "trace.capped"  # cap marker, then silence
    assert names.count("trace.capped") == 1
    assert os.path.getsize(path) < 2000  # bounded despite 200 events


# ---------------------------------------------------------------------------
# (c) health plane: /healthz, /metrics, /debug/queries, heartbeat gauges
# ---------------------------------------------------------------------------


_PROM_LINE = re.compile(
    r"^(?:# (?:HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^}]*\})? -?[0-9.e+-]+)$")


def _parse_prometheus(text: str) -> dict:
    """Validate the exposition format line by line; return
    {family: {labelset_str: value}}."""
    out = {}
    for line in text.rstrip("\n").split("\n"):
        assert _PROM_LINE.match(line), f"bad prometheus line: {line!r}"
        if line.startswith("#"):
            continue
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (.+)$", line)
        fam, labels, val = m.groups()
        out.setdefault(fam, {})[labels or ""] = float(val)
    return out


def test_render_prometheus_format_and_registry_gate():
    text = render_prometheus([
        ("ballista_up", {}, 1),
        ("ballista_executor_rss_bytes", {"executor": 'ab"12\\x'}, 5.5),
        ("not_registered_family", {}, 9),
    ])
    fams = _parse_prometheus(text)
    assert fams["ballista_up"][""] == 1
    assert "not_registered_family" not in fams  # registry is the gate
    # HELP/TYPE lines present per family
    assert "# TYPE ballista_up gauge" in text
    assert "# HELP ballista_executor_rss_bytes" in text


def test_cluster_health_plane_end_to_end(clean_env, tmp_path):
    from ballista_tpu.distributed.executor import LocalCluster
    from tests.procutil import http_get, wait_healthz

    os.environ["BALLISTA_SLOW_QUERY_SECS"] = "0.0"  # everything is slow
    csv = tmp_path / "t.csv"
    with open(csv, "w") as f:
        f.write("k,a\n")
        for i in range(40):
            f.write(f"{'xy'[i % 2]},{i}\n")

    cluster = LocalCluster(num_executors=2, metrics_port=0)
    try:
        sport = cluster.scheduler_health_port
        eports = [e.health_port for e in cluster.executors]
        assert sport and all(eports)
        assert wait_healthz(sport)["role"] == "scheduler"
        for p in eports:
            assert wait_healthz(p)["role"] == "executor"

        ctx = BallistaContext.remote("localhost", cluster.port)
        ctx.register_csv("t", str(csv), schema(("k", Utf8), ("a", Int64)))
        out = ctx.sql(
            "SELECT k, sum(a) AS s FROM t GROUP BY k ORDER BY k").collect()
        assert list(out["s"]) == [380, 400]

        # wait until a post-completion heartbeat delivered gauges
        deadline = time.time() + 15
        fams = {}
        while time.time() < deadline:
            fams = _parse_prometheus(http_get(sport, "/metrics"))
            if fams.get("ballista_jobs_completed_total", {}).get("") == 1 \
                    and len(fams.get("ballista_executor_rss_bytes", {})) == 2:
                break
            time.sleep(0.1)
        # scheduler aggregate: job counters + BOTH executors' resource
        # gauges, labelled per executor, with live rss values
        assert fams["ballista_jobs_submitted_total"][""] == 1
        assert fams["ballista_jobs_completed_total"][""] == 1
        assert fams["ballista_executors_live"][""] == 2
        rss = fams["ballista_executor_rss_bytes"]
        assert len(rss) == 2 and all(v > 0 for v in rss.values())
        assert len(fams["ballista_executor_inflight_tasks"]) == 2
        assert fams["ballista_tasks_dispatched_total"][""] >= 2

        # executor-local /metrics: task counters + process memory
        efams = _parse_prometheus(http_get(eports[0], "/metrics"))
        assert efams["ballista_up"][""] == 1
        assert "ballista_tasks_completed_total" in efams
        assert efams["ballista_rss_bytes"][""] > 0

        # /debug/queries: ring buffer carries the job, slow log caught
        # it (threshold 0), and the executor ring shows its tasks
        dbg = json.loads(http_get(sport, "/debug/queries"))
        assert any(q.get("state") == "completed" for q in dbg["queries"])
        assert dbg["slow_queries"] and dbg["slow_query_secs"] == 0.0
        job = dbg["queries"][-1]
        assert job["wall_seconds"] > 0 and job["num_stages"] >= 2
        edbg = json.loads(http_get(eports[0], "/debug/queries"))
        assert isinstance(edbg["queries"], list)
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# (d) memory accounting: categories, monotone peaks, operator gauges
# ---------------------------------------------------------------------------


def test_memory_accounting_monotone_and_categories():
    base_peak = obs_memory.peak_host_bytes()
    obs_memory.record_host_bytes("batches", 1000)
    p1 = obs_memory.peak_host_bytes()
    obs_memory.record_host_bytes("batches", 500)
    p2 = obs_memory.peak_host_bytes()
    obs_memory.release_host_bytes("batches", 1500)
    p3 = obs_memory.peak_host_bytes()
    # peaks are monotone within a query window: release never lowers
    assert base_peak <= p1 <= p2 == p3
    snap = obs_memory.host_memory_snapshot()
    assert snap["peak_by_category"]["batches"] >= 1500
    # double release clamps rather than going negative
    obs_memory.release_host_bytes("batches", 10_000_000)
    assert obs_memory.host_memory_snapshot()["by_category"]["batches"] >= 0


def test_peak_memory_gauges_per_operator(ctx):
    ctx.sql("SELECT k, sum(a) AS s FROM t GROUP BY k").collect()
    qm = ctx.last_query_metrics()
    gauged = [r for r in qm.operators()
              if "peak_host_bytes" in r["metrics"]]
    assert gauged, qm.pretty()
    proc_peak = obs_memory.peak_host_bytes()
    for r in gauged:
        v = r["metrics"]["peak_host_bytes"]
        assert 0 < v <= proc_peak  # operator peak within process peak
    # EXPLAIN ANALYZE surfaces the memory plane
    out = ctx.sql(
        "EXPLAIN ANALYZE SELECT k, sum(a) AS s FROM t GROUP BY k").collect()
    rows = dict(zip(out["plan_type"], out["plan"]))
    assert "peak_host_bytes=" in rows["memory"]
    assert "peak_device_bytes=" in rows["memory"]


def test_dictionary_and_cache_categories_populate(tmp_path):
    tbl = tmp_path / "d.tbl"
    tbl.write_text("".join(f"{i}|v{i % 7}|\n" for i in range(50)))
    ctx = BallistaContext.standalone()
    ctx.register_tbl("d", str(tbl), schema(("a", Int64), ("c", Utf8)),
                     cached=True)
    ctx.sql("SELECT c, count(*) AS n FROM d GROUP BY c").collect()
    snap = obs_memory.host_memory_snapshot()
    assert snap["peak_by_category"].get("dictionaries", 0) > 0
    assert snap["peak_by_category"].get("cache", 0) > 0


# ---------------------------------------------------------------------------
# (e) lint + overhead gate
# ---------------------------------------------------------------------------


def test_metric_name_registry_lint():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "dev", "check_metric_names.py")],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stdout + r.stderr


def test_profiler_endpoints_overhead_q1_under_5pct(tmp_path_factory,
                                                   clean_env):
    """Warm q1 with the observability surfaces of this PR enabled
    (tracing to a file + a live health server answering scrapes) stays
    within 5% of all-off — the drift-cancelling scheme from PR 1's
    metrics gate (alternating interleaved samples, medians, retries)."""
    from benchmarks.tpch import datagen
    from benchmarks.tpch.schema_def import register_tpch
    from ballista_tpu.observability.health import HealthServer
    from tests.procutil import http_get

    data_dir = str(tmp_path_factory.mktemp("tpch_prof"))
    datagen.generate(data_dir, scale=0.01, num_parts=1)
    ctx = BallistaContext.standalone()
    register_tpch(ctx, data_dir, "tbl")
    qdir = os.path.join(REPO, "benchmarks", "tpch", "queries")
    df = ctx.sql(open(os.path.join(qdir, "q1.sql")).read())
    df.collect()  # warm: jit compile + table caches

    trace_path = str(tmp_path_factory.mktemp("trace") / "t.jsonl")
    server = HealthServer("test", 0,
                          samples_fn=lambda: [
                              ("ballista_inflight_tasks", {}, 0)])

    def set_enabled(on: bool):
        if on:
            os.environ["BALLISTA_TRACE"] = "1"
            os.environ["BALLISTA_TRACE_FILE"] = trace_path
        else:
            os.environ.pop("BALLISTA_TRACE", None)
            os.environ.pop("BALLISTA_TRACE_FILE", None)
        obs_tracing.reconfigure()

    def sample(on: bool):
        set_enabled(on)
        if on:
            # a scrape between samples: endpoints live and answering
            # while queries run, but out-of-band like a real scraper —
            # not serialized into the query's critical path
            http_get(server.port, "/metrics")
        t0 = time.perf_counter()
        for _ in range(3):
            df.collect()
        return time.perf_counter() - t0

    try:
        sample(True)
        sample(False)

        def measure():
            offs, ons = [], []
            for i in range(9):
                if i % 2 == 0:
                    offs.append(sample(False))
                    ons.append(sample(True))
                else:
                    ons.append(sample(True))
                    offs.append(sample(False))
            return sorted(offs)[4], sorted(ons)[4]

        for _attempt in range(3):
            t_off, t_on = measure()
            if t_on <= t_off * 1.05 + 2e-3:
                return
        overhead = (t_on - t_off) / t_off
        raise AssertionError(
            f"profiler/endpoints overhead {overhead:.1%} "
            f"(on={t_on:.4f}s off={t_off:.4f}s)")
    finally:
        server.close()
        set_enabled(False)
