"""SQL-queryable ``system.*`` tables + durable query history (ISSUE 8).

Covers: SELECT over every system table on the standalone path (queries
ring with status/wall/rows/digest, flight-recorder lanes, deferred
operator metrics, compile-governor entries, the settings registry, the
self executor row); the shared-record contract with ``/debug/queries``;
the durable history log (rotation, restart survival via a subprocess);
LocalCluster e2e (``system.executors`` lists both executors with
heartbeat resources, a slow query lands in ``system.queries`` with its
plan digest + artifact path, lanes annotate cluster jobs); serde of
materialized system scans; the knob-docs lint; and the < 5% warm-q1
overhead gate extended to the history-log write path."""

import json
import os
import subprocess
import sys
import time

import pytest

from ballista_tpu.client import BallistaContext
from ballista_tpu.datatypes import Float64, Int64, Utf8, schema
from ballista_tpu.observability import systables
from ballista_tpu.observability.export import LANE_NAMES

REPO = os.path.join(os.path.dirname(__file__), "..")


@pytest.fixture
def ctx():
    c = BallistaContext.standalone()
    c.register_memtable(
        "t", schema(("k", Utf8), ("a", Int64), ("b", Float64)),
        {"k": ["x", "y", "z"] * 20,
         "a": list(range(60)),
         "b": [float(i) / 4 for i in range(60)]},
    )
    return c


@pytest.fixture
def clean_env():
    keys = ("BALLISTA_QUERY_LOG_DIR", "BALLISTA_QUERY_LOG_MAX_MB",
            "BALLISTA_PROFILE", "BALLISTA_SLOW_QUERY_SECS",
            "BALLISTA_SLOW_QUERY_DIR", "BALLISTA_TRACE",
            "BALLISTA_TRACE_FILE")
    saved = {k: os.environ.get(k) for k in keys}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _fresh_select(ctx, sql):
    """System-table scans rebuild rows per collect, but assertions about
    queries recorded BETWEEN two identical SELECTs need a fresh plan —
    drop the SQL plan cache to keep the test honest about that."""
    ctx._plan_cache.clear()
    return ctx.sql(sql).collect()


# ---------------------------------------------------------------------------
# standalone path
# ---------------------------------------------------------------------------


def test_system_queries_standalone(ctx, clean_env):
    out = ctx.sql(
        "SELECT k, sum(a) AS s FROM t GROUP BY k ORDER BY k").collect()
    assert len(out) == 3
    q = _fresh_select(
        ctx, "SELECT job_id, plan_digest, status, wall_seconds, "
             "output_rows, origin FROM system.queries")
    # the SELECT over system.queries is itself an in-flight
    # status="running" row — assert on the last *completed* query
    done = q[q["status"] == "completed"]
    assert len(done) >= 1
    row = done.iloc[-1]
    assert row["status"] == "completed"
    assert row["origin"] == "standalone"
    assert row["job_id"].startswith("local-")
    assert len(row["plan_digest"]) == 12
    assert row["wall_seconds"] > 0
    assert row["output_rows"] == 3
    # ORDER BY over a system table is an ordinary plan
    q2 = _fresh_select(
        ctx, "SELECT job_id, wall_seconds FROM system.queries "
             "ORDER BY wall_seconds DESC LIMIT 3")
    assert len(q2) >= 1
    assert list(q2["wall_seconds"]) == sorted(q2["wall_seconds"],
                                              reverse=True)


def test_system_query_lanes_standalone(ctx, clean_env):
    ctx.sql("SELECT sum(a) AS s FROM t").collect()
    lanes = _fresh_select(
        ctx, "SELECT job_id, lane, seconds, fraction "
             "FROM system.query_lanes")
    assert len(lanes) >= len(LANE_NAMES)
    got = set(lanes["lane"])
    assert got <= set(LANE_NAMES)
    # every recorded query carries the full lane set
    last_job = lanes.iloc[-1]["job_id"]
    per_query = lanes[lanes["job_id"] == last_job]
    assert set(per_query["lane"]) == set(LANE_NAMES)
    assert (per_query["seconds"] >= 0).all()


def test_system_operators_standalone(ctx, clean_env):
    ctx.sql("SELECT k, sum(a) AS s FROM t GROUP BY k").collect()
    ops = _fresh_select(
        ctx, "SELECT operator, metric, value FROM system.operators "
             "WHERE metric = 'output_rows'")
    assert len(ops) >= 1
    scans = ops[ops["operator"].str.startswith("ScanExec: t")]
    assert len(scans) >= 1 and float(scans.iloc[-1]["value"]) == 60.0


def test_system_operators_stale_epoch_dropped(ctx, clean_env):
    # two un-harvested collects of the SAME cached plan: the second
    # run's metric reset bumps the plan's epoch, so the FIRST run's
    # deferred snapshot must decline (its values were clobbered) while
    # the second harvests fine — never the second run's numbers under
    # the first run's job id
    df = ctx.sql("SELECT sum(b) AS s FROM t")
    df.collect()
    job_a = systables.process_query_log().snapshot()["queries"][-1]["job_id"]
    df.collect()
    job_b = systables.process_query_log().snapshot()["queries"][-1]["job_id"]
    assert job_a != job_b
    jobs = {r["job_id"] for r in systables.operator_store().rows()}
    assert job_b in jobs
    assert job_a not in jobs


def test_system_settings(ctx, clean_env, monkeypatch):
    s = _fresh_select(
        ctx, "SELECT name, value, source, description "
             "FROM system.settings WHERE name = 'BALLISTA_FUSION'")
    assert len(s) == 1
    assert s.iloc[0]["value"] == "on" and s.iloc[0]["source"] == "default"
    monkeypatch.setenv("BALLISTA_FUSION", "0")
    s = _fresh_select(
        ctx, "SELECT value, source FROM system.settings "
             "WHERE name = 'BALLISTA_FUSION'")
    assert s.iloc[0]["value"] == "0" and s.iloc[0]["source"] == "env"
    # registry completeness: every registered knob appears exactly once
    all_rows = _fresh_select(ctx, "SELECT name FROM system.settings")
    names = list(all_rows["name"])
    for knob in systables.KNOBS:
        assert names.count(knob) == 1


def test_system_compile_and_executors(ctx, clean_env):
    ctx.sql("SELECT k, sum(a) AS s FROM t GROUP BY k").collect()
    c = _fresh_select(
        ctx, "SELECT namespace, signature, calls, compiles "
             "FROM system.compile")
    assert len(c) >= 1 and (c["calls"] >= 0).all()
    e = _fresh_select(ctx, "SELECT * FROM system.executors")
    assert len(e) == 1
    row = e.iloc[0]
    assert row["executor_id"] == "standalone"
    assert row["rss_bytes"] > 0 and row["num_devices"] >= 1


def test_dataframe_api_and_explain(ctx, clean_env):
    ctx.sql("SELECT sum(a) AS s FROM t").collect()
    df = ctx.table("system.settings")
    out = df.collect()
    assert len(out) == len(systables.settings_rows())
    plan = ctx.sql("EXPLAIN SELECT * FROM system.queries").collect()
    assert "TableScan: system.queries" in plan["plan"][0]
    txt = ctx.sql(
        "EXPLAIN ANALYZE SELECT count(*) AS n FROM system.settings"
    ).collect()
    rendered = dict(zip(txt["plan_type"], txt["plan"]))
    assert "ScanExec: system.settings" in rendered["plan_with_metrics"]


def test_system_plans_not_cached_joins_stay_fresh(ctx, clean_env):
    # a join over system tables materializes its build side per plan
    # instance: ctx.sql must NOT serve a cached plan for system scans,
    # or a re-issued query would join fresh probe rows against the
    # FIRST collect's frozen build-side snapshot
    sql = ("SELECT q.job_id FROM system.queries q, system.query_lanes l "
           "WHERE q.job_id = l.job_id")
    ctx.sql("SELECT sum(a) AS s FROM t").collect()
    ctx.sql(sql).collect()
    assert sql not in ctx._plan_cache
    ctx.sql("SELECT sum(b) AS s2 FROM t").collect()
    new_job = systables.process_query_log().snapshot()["queries"][-1]["job_id"]
    second = ctx.sql(sql).collect()  # same SQL text, no cache clearing
    assert new_job in set(second["q__job_id"])


def test_failed_query_recorded(ctx, clean_env, tmp_path):
    # valid plan (the file exists at registration), fails at EXECUTION:
    # the file vanishes before the scan runs
    path = tmp_path / "ghost.csv"
    path.write_text("k,a\nx,1\n")
    ctx.register_csv("ghost", str(path), schema(("k", Utf8), ("a", Int64)))
    path.unlink()
    with pytest.raises(Exception):
        ctx.sql("SELECT sum(a) AS s FROM ghost").collect()
    q = _fresh_select(
        ctx, "SELECT status, error FROM system.queries "
             "WHERE status = 'failed'")
    assert len(q) >= 1
    assert q.iloc[-1]["error"]


# ---------------------------------------------------------------------------
# shared-record contract (/debug/queries <-> system.queries)
# ---------------------------------------------------------------------------


def test_debug_queries_shares_record_shape(ctx, clean_env):
    ctx.sql("SELECT sum(a) AS s FROM t").collect()
    snap = systables.process_query_log().snapshot()
    entry = snap["queries"][-1]
    # the satellite contract: ring entries carry status, wall_seconds
    # and output_rows — the same fields system.queries serves
    assert entry["status"] == "completed"
    assert entry["state"] == "completed"  # legacy alias intact
    assert entry["wall_seconds"] > 0
    assert entry["output_rows"] == 1
    assert set(entry.get("lanes", {})) <= set(LANE_NAMES)
    q = _fresh_select(
        ctx, "SELECT job_id, wall_seconds FROM system.queries")
    assert entry["job_id"] in set(q["job_id"])
    match = q[q["job_id"] == entry["job_id"]]
    assert float(match.iloc[0]["wall_seconds"]) == \
        pytest.approx(entry["wall_seconds"], abs=1e-3)


# ---------------------------------------------------------------------------
# durable history log
# ---------------------------------------------------------------------------


def test_history_log_rotation(tmp_path):
    log = systables.QueryHistoryLog(str(tmp_path), max_bytes=5000)
    for i in range(200):
        log.append({"job_id": f"j{i}", "status": "completed",
                    "wall_seconds": 0.1, "pad": "x" * 80})
    main = os.path.join(str(tmp_path), "query_history.jsonl")
    rotated = main + ".1"
    assert os.path.exists(main) and os.path.exists(rotated)
    assert os.path.getsize(main) <= 5000 + 200
    assert os.path.getsize(rotated) <= 5000 + 200
    records = log.read()
    # newest records survive; last-line-per-job dedup holds
    assert records[-1]["job_id"] == "j199"
    ids = [r["job_id"] for r in records]
    assert len(ids) == len(set(ids))


def test_history_dedups_enriched_lines(tmp_path):
    log = systables.QueryHistoryLog(str(tmp_path))
    log.append({"job_id": "a", "status": "completed", "wall_seconds": 1})
    log.append({"job_id": "a", "status": "completed", "wall_seconds": 1,
                "lanes": {"parse": 0.5}})
    recs = log.read()
    assert len(recs) == 1 and recs[0]["lanes"] == {"parse": 0.5}


def test_history_survives_process_restart(ctx, clean_env, tmp_path,
                                          monkeypatch):
    """The acceptance gate: rows written under BALLISTA_QUERY_LOG_DIR
    are SELECTable from a FRESH process (its in-memory ring is empty,
    so everything must come from disk)."""
    monkeypatch.setenv("BALLISTA_QUERY_LOG_DIR", str(tmp_path))
    ctx.sql("SELECT k, sum(a) AS s FROM t GROUP BY k").collect()
    snap = systables.process_query_log().snapshot()
    job_id = snap["queries"][-1]["job_id"]
    hist = os.path.join(str(tmp_path), "query_history.jsonl")
    assert os.path.exists(hist)
    code = (
        "import json, os\n"
        "from ballista_tpu.client import BallistaContext\n"
        "ctx = BallistaContext.standalone()\n"
        "q = ctx.sql('SELECT job_id, status, output_rows, origin '\n"
        "            'FROM system.queries').collect()\n"
        "print('ROWS=' + json.dumps(q.to_dict('records')))\n"
    )
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "BALLISTA_QUERY_LOG_DIR": str(tmp_path)})
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300,
                         cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    line = next(l for l in out.stdout.splitlines()
                if l.startswith("ROWS="))
    rows = json.loads(line[len("ROWS="):])
    match = [r for r in rows if r["job_id"] == job_id]
    assert match, rows
    assert match[0]["status"] == "completed"
    assert match[0]["output_rows"] == 3
    assert match[0]["origin"] == "history"


# ---------------------------------------------------------------------------
# serde: materialized system scans cross the wire
# ---------------------------------------------------------------------------


def test_system_source_serde_roundtrip(ctx, clean_env):
    from ballista_tpu import serde

    ctx.sql("SELECT sum(a) AS s FROM t").collect()
    src = systables.SystemTableSource("system.queries")
    p = serde.source_to_proto(src)
    assert p.kind == "system" and p.path == "system.queries"
    back = serde.source_from_proto(p)
    rows = back.current_rows()
    assert rows and rows[-1]["status"] == "completed"
    # deserialized sources scan the MATERIALIZED snapshot (frozen at
    # serialization time), with NULLs masked
    batches = list(back.scan(0))
    assert batches and int(batches[0].num_rows) == len(rows)


# ---------------------------------------------------------------------------
# cluster path (satellite: LocalCluster e2e)
# ---------------------------------------------------------------------------


def test_cluster_system_tables_end_to_end(clean_env, tmp_path):
    from ballista_tpu.distributed.executor import LocalCluster
    from tests.procutil import http_get

    os.environ["BALLISTA_SLOW_QUERY_SECS"] = "0.0"  # everything is slow
    os.environ["BALLISTA_PROFILE"] = str(tmp_path / "profiles")
    os.environ["BALLISTA_QUERY_LOG_DIR"] = str(tmp_path / "qlog")
    csv = tmp_path / "t.csv"
    with open(csv, "w") as f:
        f.write("k,a\n")
        for i in range(40):
            f.write(f"{'xy'[i % 2]},{i}\n")

    cluster = LocalCluster(num_executors=2, metrics_port=0)
    try:
        ctx = BallistaContext.remote("localhost", cluster.port)
        ctx.register_csv("t", str(csv), schema(("k", Utf8), ("a", Int64)))

        # system.executors BEFORE any job: both executors, heartbeat
        # resource columns populated (scheduler-side state)
        deadline = time.time() + 30
        while time.time() < deadline:
            e = _fresh_select(ctx, "SELECT * FROM system.executors")
            if len(e) == 2 and (e["rss_bytes"] > 0).all():
                break
            time.sleep(0.2)
        assert len(e) == 2, e
        assert (e["rss_bytes"] > 0).all()
        assert set(e.columns) >= {"executor_id", "host", "port",
                                  "num_devices", "rss_bytes",
                                  "device_bytes", "inflight_tasks",
                                  "ingest_pool_depth", "peak_host_bytes"}

        out = ctx.sql(
            "SELECT k, sum(a) AS s FROM t GROUP BY k ORDER BY k"
        ).collect()
        assert list(out["s"]) == [380, 400]
        job_id = ctx._last_job_id
        assert job_id

        # the slow query (threshold 0) lands in system.queries with its
        # plan digest; the deferred worker attaches the merged profile
        # artifact path + lanes shortly after the terminal transition
        row = lanes = None
        deadline = time.time() + 30
        while time.time() < deadline:
            q = _fresh_select(
                ctx, "SELECT job_id, status, plan_digest, output_rows, "
                     "profile_artifact, origin FROM system.queries")
            match = q[q["job_id"] == job_id]
            pa = match.iloc[0]["profile_artifact"] if len(match) else None
            if isinstance(pa, str) and pa:
                row = match.iloc[0]
                lanes = _fresh_select(
                    ctx, "SELECT job_id, lane, seconds "
                         "FROM system.query_lanes")
                lanes = lanes[lanes["job_id"] == job_id]
                if len(lanes):
                    break
            time.sleep(0.25)
        assert row is not None, "job never got its artifact annotation"
        assert row["status"] == "completed"
        assert row["origin"] == "cluster"
        assert len(row["plan_digest"]) == 12
        assert int(row["output_rows"]) == 2
        assert os.path.exists(row["profile_artifact"])
        assert set(lanes["lane"]) == set(LANE_NAMES)

        # cluster operator metrics are queryable
        ops = _fresh_select(
            ctx, "SELECT job_id, operator, value FROM system.operators "
                 "WHERE metric = 'output_rows'")
        assert job_id in set(ops["job_id"])

        # history log got the cluster job (restart durability is the
        # standalone subprocess test's job; here: the line exists and
        # carries the digest)
        hist = systables.QueryHistoryLog(
            str(tmp_path / "qlog")).read()
        match = [r for r in hist if r.get("job_id") == job_id]
        assert match and match[-1]["plan_digest"] == row["plan_digest"]

        # /debug/queries serves the SAME record shape (shared builder):
        # status + wall_seconds + output_rows on the ring entries
        dbg = json.loads(http_get(cluster.scheduler_health_port,
                                  "/debug/queries"))
        entry = next(d for d in dbg["queries"]
                     if d.get("job_id") == job_id)
        assert entry["status"] == "completed"
        assert entry["wall_seconds"] > 0
        assert entry["output_rows"] == 2
        assert dbg["slow_queries"], "threshold 0 query missed slow ring"
    finally:
        cluster.shutdown()
        for k in ("BALLISTA_SLOW_QUERY_SECS", "BALLISTA_PROFILE",
                  "BALLISTA_QUERY_LOG_DIR"):
            os.environ.pop(k, None)


# ---------------------------------------------------------------------------
# lint + overhead gate
# ---------------------------------------------------------------------------


def test_knob_docs_lint():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "dev", "check_knob_docs.py")],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr + out.stdout


def test_query_history_overhead_q1_under_5pct(tmp_path_factory,
                                              clean_env):
    """Warm q1 with the query-history log ENABLED (every collect
    appends a JSON line) stays within 5% of disabled — the
    drift-cancelling scheme from the PR 1/5 gates (alternating
    interleaved samples, medians, retries). The always-on parts of the
    recorder (ring record, lanes from the flight recorder) are present
    in BOTH samples by design — this gates the satellite's target, the
    history WRITE path."""
    from benchmarks.tpch import datagen
    from benchmarks.tpch.schema_def import register_tpch

    data_dir = str(tmp_path_factory.mktemp("tpch_hist"))
    log_dir = str(tmp_path_factory.mktemp("qlog"))
    datagen.generate(data_dir, scale=0.01, num_parts=1)
    ctx = BallistaContext.standalone()
    register_tpch(ctx, data_dir, "tbl")
    qdir = os.path.join(REPO, "benchmarks", "tpch", "queries")
    df = ctx.sql(open(os.path.join(qdir, "q1.sql")).read())
    df.collect()  # warm: jit compile + table caches

    def set_enabled(on: bool):
        if on:
            os.environ["BALLISTA_QUERY_LOG_DIR"] = log_dir
        else:
            os.environ.pop("BALLISTA_QUERY_LOG_DIR", None)

    def sample(on: bool):
        set_enabled(on)
        t0 = time.perf_counter()
        for _ in range(3):
            df.collect()
        return time.perf_counter() - t0

    try:
        sample(True)
        sample(False)

        def measure():
            offs, ons = [], []
            for i in range(9):
                if i % 2 == 0:
                    offs.append(sample(False))
                    ons.append(sample(True))
                else:
                    ons.append(sample(True))
                    offs.append(sample(False))
            return sorted(offs)[4], sorted(ons)[4]

        for _attempt in range(3):
            t_off, t_on = measure()
            if t_on <= t_off * 1.05 + 2e-3:
                break
        else:
            overhead = (t_on - t_off) / t_off
            raise AssertionError(
                f"query-history overhead {overhead:.1%} "
                f"(on={t_on:.4f}s off={t_off:.4f}s)")
        # the enabled samples really wrote history lines
        hist = systables.QueryHistoryLog(log_dir).read()
        assert hist and hist[-1]["status"] == "completed"
    finally:
        set_enabled(False)
