"""Composite join keys beyond the 2-column / 31-bit packing limit.

Keys are iteratively ranked against the build side (exact — no hash
collisions), so any number/width of key columns works; the round-1
ExecutionError for out-of-range 2-column keys is gone.
"""

import numpy as np
import pandas as pd
import pytest

from ballista_tpu import schema, Int64, Utf8
from ballista_tpu.client import BallistaContext
from ballista_tpu.io import MemTableSource


def _ctx_with(tables):
    ctx = BallistaContext.standalone()
    for name, (s, data) in tables.items():
        ctx.register_source(name, MemTableSource.from_pydict(s, data,
                                                             num_partitions=2))
    return ctx


def test_three_key_inner_join():
    rng = np.random.default_rng(3)
    n = 400
    a = rng.integers(0, 5, n)
    b = rng.integers(0, 7, n)
    c = rng.integers(0, 3, n)
    v = rng.integers(0, 100, n)
    left = {"a": a, "b": b, "c": c, "v": v}
    m = 60
    rb = rng.integers(0, 5, m)
    sb = rng.integers(0, 7, m)
    tb = rng.integers(0, 3, m)
    w = rng.integers(0, 100, m)
    right = {"x": rb, "y": sb, "z": tb, "w": w}

    ls = schema(("a", Int64), ("b", Int64), ("c", Int64), ("v", Int64))
    rs = schema(("x", Int64), ("y", Int64), ("z", Int64), ("w", Int64))
    ctx = _ctx_with({"l": (ls, left), "r": (rs, right)})
    got = ctx.sql(
        "select sum(v + w) as s, count(*) as n from l, r "
        "where a = x and b = y and c = z"
    ).collect()

    ld = pd.DataFrame(left)
    rd = pd.DataFrame(right)
    j = ld.merge(rd, left_on=["a", "b", "c"], right_on=["x", "y", "z"])
    assert int(got["n"][0]) == len(j)
    assert int(got["s"][0]) == int((j.v + j.w).sum())


def test_two_key_join_beyond_packing_range():
    """Round 1 raised 'exceed the packable 31/32-bit range' here."""
    big = np.int64(1) << 40
    left = {"a": np.array([big, big + 1, big + 2, 5], np.int64),
            "b": np.array([-7, -7, 9, 9], np.int64),
            "v": np.arange(4)}
    right = {"x": np.array([big, big + 2, big + 9], np.int64),
             "y": np.array([-7, 9, 9], np.int64),
             "w": np.array([10, 20, 30])}
    ls = schema(("a", Int64), ("b", Int64), ("v", Int64))
    rs = schema(("x", Int64), ("y", Int64), ("w", Int64))
    ctx = _ctx_with({"l": (ls, left), "r": (rs, right)})
    got = ctx.sql(
        "select v, w from l, r where a = x and b = y order by v"
    ).collect()
    assert list(got["v"]) == [0, 2]
    assert list(got["w"]) == [10, 20]


def test_utf8_join_key_across_dictionaries():
    """Joining on a string column across two tables: each side has its
    own dictionary, so codes are incomparable — probe codes are remapped
    into the build dictionary's space (strings absent from the build
    never match)."""
    left = {"name": ["delta", "alpha", "echo", "bravo"],
            "v": np.arange(4)}
    right = {"label": ["bravo", "alpha", "zulu"],
             "w": np.array([10, 20, 30])}
    ls = schema(("name", Utf8), ("v", Int64))
    rs = schema(("label", Utf8), ("w", Int64))
    ctx = _ctx_with({"l": (ls, left), "r": (rs, right)})
    got = ctx.sql(
        "select v, w from l, r where name = label order by v"
    ).collect()
    # alpha->20 (v=1), bravo->10 (v=3); delta/echo unmatched; zulu unused
    assert list(got["v"]) == [1, 3]
    assert list(got["w"]) == [20, 10]

    # left join preserves non-matching strings
    got2 = ctx.sql(
        "select v, w from l left join r on name = label order by v"
    ).collect()
    assert list(got2["v"]) == [0, 1, 2, 3]
    w = got2["w"].astype(float).to_numpy()
    assert np.isnan(w[0]) and w[1] == 20 and np.isnan(w[2]) and w[3] == 10


def test_three_key_left_join_with_duplicates():
    left = {"a": np.array([1, 1, 2, 3]), "b": np.array([1, 1, 2, 2]),
            "c": np.array([0, 0, 0, 0]), "v": np.arange(4)}
    # duplicate build keys -> expansion; key (3,2,0) unmatched
    right = {"x": np.array([1, 1, 2]), "y": np.array([1, 1, 2]),
             "z": np.array([0, 0, 0]), "w": np.array([5, 6, 7])}
    ls = schema(("a", Int64), ("b", Int64), ("c", Int64), ("v", Int64))
    rs = schema(("x", Int64), ("y", Int64), ("z", Int64), ("w", Int64))
    ctx = _ctx_with({"l": (ls, left), "r": (rs, right)})
    got = ctx.sql(
        "select v, w from l left join r on a = x and b = y and c = z "
        "order by v, w"
    ).collect()
    ld, rd = pd.DataFrame(left), pd.DataFrame(right)
    exp = ld.merge(rd, how="left", left_on=["a", "b", "c"],
                   right_on=["x", "y", "z"])[["v", "w"]] \
        .sort_values(["v", "w"]).reset_index(drop=True)
    assert len(got) == len(exp)
    np.testing.assert_array_equal(got["v"], exp["v"])
    got_w = got["w"].astype(float).to_numpy()
    exp_w = exp["w"].astype(float).to_numpy()
    np.testing.assert_array_equal(np.isnan(got_w), np.isnan(exp_w))
    np.testing.assert_array_equal(got_w[~np.isnan(got_w)],
                                  exp_w[~np.isnan(exp_w)])
