"""Pipelined parallel ingest (ballista_tpu/ingest): determinism, memory
bounds, cross-table overlap, cache-source concurrency, observability.

The pipeline reorders TIMING, never rows: TPC-H results must be
byte-identical with the pipeline ON vs OFF and at any thread count
(same style as tests/test_mt_scan.py's single- vs multi-thread sweep).
"""

import os
import threading
import time

import numpy as np
import pytest

from ballista_tpu import schema, Int64, Utf8


QDIR = os.path.join(os.path.dirname(__file__), "..", "benchmarks", "tpch",
                    "queries")


def _configure(monkeypatch, threads, prefetch):
    from ballista_tpu import ingest

    monkeypatch.setenv("BALLISTA_INGEST_THREADS", str(threads))
    monkeypatch.setenv("BALLISTA_PREFETCH_BATCHES", str(prefetch))
    ingest.reconfigure()


@pytest.fixture(autouse=True)
def _restore_ingest_config(monkeypatch):
    """Every test leaves the process with env-default ingest config."""
    from ballista_tpu import ingest

    yield
    monkeypatch.undo()
    ingest.reconfigure()


# ---------------------------------------------------------------------------
# determinism sweep: pipeline ON vs OFF, 1 vs 4 threads
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tpch_dir(tmp_path_factory):
    from benchmarks.tpch import datagen

    d = str(tmp_path_factory.mktemp("ingest_tpch"))
    datagen.generate(d, scale=0.002, num_parts=2)
    return d


def _run_tpch(data_dir, qname):
    from ballista_tpu.client import BallistaContext
    from benchmarks.tpch.schema_def import register_tpch

    ctx = BallistaContext.standalone()
    register_tpch(ctx, data_dir, "tbl")
    sql = open(os.path.join(QDIR, f"{qname}.sql")).read()
    return ctx.sql(sql).collect()


def _assert_byte_identical(a, b, tag):
    assert list(a.columns) == list(b.columns), tag
    assert len(a) == len(b), tag
    for c in a.columns:
        ga, gb = a[c].to_numpy(), b[c].to_numpy()
        assert ga.dtype == gb.dtype, f"{tag}.{c}: {ga.dtype} vs {gb.dtype}"
        if ga.dtype.kind in "fc":  # byte-identical, not merely close
            assert ga.tobytes() == gb.tobytes(), f"{tag}.{c}"
        else:
            np.testing.assert_array_equal(ga, gb, err_msg=f"{tag}.{c}")


@pytest.mark.parametrize("qname", ["q1", "q5"])
def test_determinism_pipeline_on_off(tpch_dir, monkeypatch, qname):
    """q1 (chunked agg scan) and q5 (8-table join tree + AQE) must be
    byte-identical across serial / single-thread / wide configs."""
    _configure(monkeypatch, 1, 0)  # serial baseline (pipeline OFF)
    base = _run_tpch(tpch_dir, qname)
    for threads in (1, 4):
        _configure(monkeypatch, threads, 2)
        got = _run_tpch(tpch_dir, qname)
        _assert_byte_identical(base, got, f"{qname}[threads={threads}]")


# ---------------------------------------------------------------------------
# bounded memory: the prefetch queue never exceeds its configured depth
# ---------------------------------------------------------------------------


def _write_tbl(tmp_path, rows=1024):
    p = tmp_path / "t.tbl"
    p.write_text("".join(f"{i}|k{i % 13}|\n" for i in range(rows)))
    return str(p)


SCHEMA = schema(("a", Int64), ("c", Utf8))


def test_prefetch_queue_bounded(tmp_path, monkeypatch):
    """A slow consumer must cap the producer at the configured depth —
    the pipeline trades bounded extra memory for overlap, never
    unbounded buffering."""
    _configure(monkeypatch, 2, 2)
    from ballista_tpu.ingest import PrefetchHandle, prefetch_batches
    from ballista_tpu.io import TblSource

    assert prefetch_batches() == 2
    src = TblSource(_write_tbl(tmp_path), SCHEMA, batch_capacity=128)
    handle = PrefetchHandle(lambda: src.scan(0), depth=2, label="t[0]")
    got = 0
    for batch in handle:
        time.sleep(0.02)  # consumer slower than the parser
        got += 1
    assert got == 8  # 1024 rows / 128-capacity chunks
    assert handle.max_occupancy <= 2, handle.max_occupancy


def test_prefetch_cancel_stops_producer(tmp_path, monkeypatch):
    """A consumer abandoning the stream early (LimitExec) must not leave
    the producer blocked on a full queue."""
    _configure(monkeypatch, 2, 1)
    from ballista_tpu.io import TblSource
    from ballista_tpu.physical.operators import ScanExec

    src = TblSource(_write_tbl(tmp_path), SCHEMA, batch_capacity=128)
    scan = ScanExec("t", src)
    it = scan.execute(0)
    next(it)
    it.close()  # abandon: GeneratorExit runs ScanExec's finally
    with scan._primed_lock:
        assert not scan._primed
    # the shared pool must be usable afterwards (producer exited)
    from ballista_tpu.ingest import ingest_pool

    assert ingest_pool().submit(lambda: 42).result(timeout=10) == 42


# ---------------------------------------------------------------------------
# cross-table overlap: primed scans parse CONCURRENTLY
# ---------------------------------------------------------------------------


def test_primed_scans_parse_concurrently(monkeypatch):
    """Two primed leaf scans rendezvous at a barrier inside their scan
    bodies: only concurrent producers can both arrive (a serial pull
    loop would break the barrier's timeout)."""
    _configure(monkeypatch, 2, 1)
    from ballista_tpu.columnar import ColumnBatch
    from ballista_tpu.logical import TableSource
    from ballista_tpu.physical.operators import ScanExec

    barrier = threading.Barrier(2)
    sch = schema(("a", Int64))

    class RendezvousSource(TableSource):
        def table_schema(self):
            return sch

        def num_partitions(self):
            return 1

        def source_descriptor(self):
            return {"kind": "memory"}

        def scan(self, partition, projection=None):
            barrier.wait(timeout=30)  # fails the test if run serially
            yield ColumnBatch.from_pydict(sch, {"a": [1, 2, 3]})

    scans = [ScanExec(f"t{i}", RendezvousSource()) for i in range(2)]
    from ballista_tpu.ingest import prime_plan

    for s in scans:
        assert prime_plan(s) == 1
    for s in scans:
        batches = list(s.execute(0))
        assert int(batches[0].num_rows) == 3


def test_iter_partitions_preserves_order(monkeypatch):
    """Concurrent partition production must still yield partition 0's
    batches first, then 1's, ... — the merge order (and therefore every
    result) is identical to the serial loop even when later partitions
    finish producing first."""
    _configure(monkeypatch, 4, 2)
    from ballista_tpu.ingest import iter_partitions
    from ballista_tpu.physical.base import Partitioning, PhysicalPlan

    sch = schema(("a", Int64))

    class TaggedPlan(PhysicalPlan):
        def output_schema(self):
            return sch

        def output_partitioning(self):
            return Partitioning("unknown", 3)

        def with_new_children(self, children):
            return self

        def execute(self, partition):
            from ballista_tpu.columnar import ColumnBatch

            # later partitions finish FIRST if order were by completion
            time.sleep((3 - partition) * 0.05)
            for chunk in range(2):
                yield ColumnBatch.from_pydict(
                    sch, {"a": [partition * 10 + chunk]})

    out = [int(np.asarray(b.columns[0].values)[0])
           for b in iter_partitions(TaggedPlan(), range(3))]
    assert out == [0, 1, 10, 11, 20, 21]


# ---------------------------------------------------------------------------
# CacheSource: concurrent scans of one key materialize the inner scan once
# ---------------------------------------------------------------------------


def test_cache_source_concurrent_single_materialization(monkeypatch):
    from ballista_tpu.columnar import ColumnBatch
    from ballista_tpu.io import CacheSource
    from ballista_tpu.logical import TableSource

    sch = schema(("a", Int64))
    calls = []

    class CountingSource(TableSource):
        def table_schema(self):
            return sch

        def num_partitions(self):
            return 1

        def source_descriptor(self):
            return {"kind": "memory"}

        def scan(self, partition, projection=None):
            calls.append(partition)
            time.sleep(0.05)  # widen the race window
            yield ColumnBatch.from_pydict(sch, {"a": list(range(10))})

    cache = CacheSource(CountingSource())
    results, errors = [], []

    def worker():
        try:
            results.append(list(cache.scan(0)))
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    assert len(calls) == 1, f"inner scan ran {len(calls)} times"
    assert len(results) == 4
    for batches in results:
        assert len(batches) == 1
        assert int(batches[0].num_rows) == 10


# ---------------------------------------------------------------------------
# observability: phase split in metrics/EXPLAIN ANALYZE + trace spans
# ---------------------------------------------------------------------------


def test_phase_split_in_explain_analyze(tmp_path, monkeypatch):
    _configure(monkeypatch, 2, 2)
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.io import TblSource

    ctx = BallistaContext.standalone()
    ctx.register_source("t", TblSource(_write_tbl(tmp_path), SCHEMA))
    txt = ctx.sql(
        "SELECT c, count(*) AS n FROM t GROUP BY c").explain_analyze()
    assert "elapsed_parse" in txt, txt
    assert "elapsed_h2d" in txt, txt

    # the same split rides last_query_metrics()'s scan operator row
    # (plain collect: the ANALYZE node presents as a leaf)
    ctx.sql("SELECT sum(a) AS s FROM t").collect()
    qm = ctx.last_query_metrics()
    scan_rows = [r for r in qm.operators()
                 if r["operator"].startswith("ScanExec")]
    assert scan_rows
    assert any("elapsed_parse" in r["metrics"] for r in scan_rows)


def test_ingest_trace_spans(tmp_path, monkeypatch):
    import json

    from ballista_tpu.observability import tracing

    trace_file = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("BALLISTA_TRACE", "1")
    monkeypatch.setenv("BALLISTA_TRACE_FILE", trace_file)
    tracing.reconfigure()
    _configure(monkeypatch, 2, 2)
    try:
        from ballista_tpu.client import BallistaContext
        from ballista_tpu.io import TblSource

        ctx = BallistaContext.standalone()
        ctx.register_source("t", TblSource(_write_tbl(tmp_path), SCHEMA))
        ctx.sql("SELECT sum(a) AS s FROM t").collect()
    finally:
        monkeypatch.delenv("BALLISTA_TRACE")
        monkeypatch.delenv("BALLISTA_TRACE_FILE")
        tracing.reconfigure()
    spans = [json.loads(l) for l in open(trace_file)]
    names = {s["name"] for s in spans}
    assert "ingest.parse" in names, names
    assert "ingest.h2d" in names, names
    assert "ingest.prime" in names, names
    # parse spans carry their producer thread id, making overlap
    # observable (not inferred) in the trace
    parse = [s for s in spans if s["name"] == "ingest.parse"]
    assert all("tid" in s and "dur" in s for s in parse)


def test_phase_totals_accumulate(tmp_path, monkeypatch):
    _configure(monkeypatch, 1, 0)  # serial: phases still recorded
    from ballista_tpu import ingest
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.io import TblSource

    before = ingest.phase_totals()
    ctx = BallistaContext.standalone()
    ctx.register_source("t", TblSource(_write_tbl(tmp_path), SCHEMA))
    ctx.sql("SELECT sum(a) AS s FROM t").collect()
    after = ingest.phase_totals()
    assert after["parse"] > before["parse"]
    assert after["h2d"] > before["h2d"]
