"""Regression: the mixed-radix dense grouping path must re-derive
dictionary spans per batch. One HashAggregateExec instance executes many
partitions, and each partition's batches can carry a DIFFERENT
dictionary (per-file scans intern per-file string tables); a span cached
from a smaller first-batch dictionary would overflow the mixed-radix
digit of later batches' codes and silently collide groups (round-3
advisor finding, ballista_tpu/physical/aggregate.py)."""

import numpy as np
import pandas as pd

from ballista_tpu import schema, col, sum_, Int64, Utf8
from ballista_tpu.io import MemTableSource
from ballista_tpu.physical.aggregate import HashAggregateExec
from ballista_tpu.physical.operators import ScanExec


def _part_dict(src):
    """First batch of a single-partition source."""
    return next(src.scan(0))


def test_mixed_dict_span_grows_across_partitions():
    s = schema(("k", Utf8), ("g", Int64), ("v", Int64))
    rng = np.random.default_rng(7)

    # partition 0: tiny dictionary (2 distinct strings)
    n0 = 300
    d0 = {
        "k": [["a", "b"][i % 2] for i in range(n0)],
        "g": rng.integers(0, 10, n0),
        "v": rng.integers(0, 100, n0),
    }
    # partition 1: much larger dictionary -> codes exceed partition 0's
    # span; the buggy cached span corrupts these groups
    n1 = 400
    d1 = {
        "k": [f"x{i % 37}" for i in range(n1)],
        "g": rng.integers(0, 10, n1),
        "v": rng.integers(0, 100, n1),
    }
    b0 = _part_dict(MemTableSource.from_pydict(s, d0))
    b1 = _part_dict(MemTableSource.from_pydict(s, d1))
    assert b0.column("k").dictionary is not None
    assert len(b1.column("k").dictionary) > len(b0.column("k").dictionary)

    src = MemTableSource(s, [[b0], [b1]])
    op = HashAggregateExec(
        "partial", [col("k"), col("g")],
        [sum_(col("v")).alias("sv")], ScanExec("t", src),
    )

    for part, data in ((0, d0), (1, d1)):
        outs = list(op.execute(part))
        got = pd.concat([b.to_pandas() for b in outs], ignore_index=True)
        sum_col = [c for c in got.columns if c.endswith("sum")][0]
        got = (got.groupby(["k", "g"])[sum_col].sum().reset_index()
               .sort_values(["k", "g"]).reset_index(drop=True))
        exp = (pd.DataFrame(data).groupby(["k", "g"])["v"].sum()
               .reset_index().sort_values(["k", "g"])
               .reset_index(drop=True))
        np.testing.assert_array_equal(got["k"], exp["k"])
        np.testing.assert_array_equal(
            got["g"].astype(np.int64), exp["g"].astype(np.int64))
        np.testing.assert_array_equal(
            got[sum_col].astype(np.int64), exp["v"].astype(np.int64))
