"""Bounded-RAM streaming scan: byte-range chunked native parse.

Large files stream through the C++ scanner in byte ranges (adjacent
ranges partition rows exactly); utf8 codes remap onto table-wide
dictionaries built by one shared pre-pass. Forcing a tiny chunk size on
small data exercises the exact code path SF=10 uses.
"""

import numpy as np
import pytest

from ballista_tpu.io import native, text
from ballista_tpu import schema, Int64, Utf8
from ballista_tpu.io import TblSource


pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native scanner not built")


@pytest.fixture()
def tiny_chunks(monkeypatch):
    monkeypatch.setattr(text, "STREAM_CHUNK_BYTES", 512)


def _write(tmp_path, rows):
    p = tmp_path / "t.tbl"
    p.write_text("".join(f"{i}|k{i % 7}|{i * 3}|\n" for i in range(rows)))
    return str(p)


def test_streaming_matches_whole_file(tmp_path, tiny_chunks):
    path = _write(tmp_path, 500)
    sch = schema(("a", Int64), ("c", Utf8), ("b", Int64))
    src = TblSource(path, sch)
    batches = list(src.scan(0, ["a", "c", "b"]))
    assert len(batches) > 1  # actually streamed in several ranges
    got_a, got_b, got_codes = [], [], []
    d = None
    for b in batches:
        pyd = b.to_pydict()
        got_a.append(pyd["a"])
        got_b.append(pyd["b"])
        got_codes.append(pyd["c"])
        d = b.column("c").dictionary
    a = np.concatenate(got_a)
    np.testing.assert_array_equal(a, np.arange(500))
    np.testing.assert_array_equal(np.concatenate(got_b), np.arange(500) * 3)
    c = np.concatenate(got_codes)
    assert [c[i] for i in range(14)] == [f"k{i % 7}" for i in range(14)]
    # table-wide sorted dictionary shared by all streamed batches
    assert sorted(str(v) for v in d.values) == sorted(f"k{i}" for i in range(7))


def test_streaming_query_end_to_end(tmp_path, tiny_chunks):
    """Aggregation over a streamed table == oracle over the same rows."""
    path = _write(tmp_path, 400)
    sch = schema(("a", Int64), ("c", Utf8), ("b", Int64))
    from ballista_tpu.client import BallistaContext

    ctx = BallistaContext.standalone()
    ctx.register_source("t", TblSource(path, sch))
    out = ctx.sql(
        "SELECT c, sum(a) AS s, count(*) AS n FROM t GROUP BY c ORDER BY c"
    ).collect()
    a = np.arange(400)
    for i in range(7):
        m = a % 7 == i
        assert out["c"][i] == f"k{i}"
        assert int(out["s"][i]) == int(a[m].sum())
        assert int(out["n"][i]) == int(m.sum())


def test_streaming_nulls(tmp_path, tiny_chunks):
    """NULLs (empty fields) surface as validity across range boundaries."""
    p = tmp_path / "n.tbl"
    lines = []
    for i in range(300):
        lines.append(f"{i}|x{i % 3}||\n" if i % 5 == 0
                     else f"{i}|x{i % 3}|{i}|\n")
    p.write_text("".join(lines))
    sch = schema(("a", Int64), ("c", Utf8), ("b", Int64))
    from ballista_tpu.client import BallistaContext

    ctx = BallistaContext.standalone()
    ctx.register_source("t", TblSource(str(p), sch))
    out = ctx.sql("SELECT c, count(b) AS nb, count(*) AS n FROM t "
                  "GROUP BY c ORDER BY c").collect()
    # every 5th row is NULL in b; count(b) skips them
    assert int(out["n"].sum()) == 300
    assert int(out["nb"].sum()) == 240
