"""Server-side SQL planning: raw SQL + catalog travel to the scheduler
(parity with the reference's sql-or-plan ExecuteQuery,
rust/scheduler/src/lib.rs:236-247 — which the round-1 scheduler rejected).
"""

import numpy as np
import pytest

from ballista_tpu import schema, Int64, Utf8
from ballista_tpu.client import BallistaContext
from ballista_tpu.distributed.executor import LocalCluster
from ballista_tpu.distributed.scheduler import SchedulerService
from ballista_tpu.distributed.state import MemoryBackend, SchedulerState
from ballista_tpu.errors import ClusterError
from ballista_tpu.io import TblSource
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu import serde


def _tbl(tmp_path):
    p = tmp_path / "t.tbl"
    p.write_text("".join(f"{i}|k{i % 3}|\n" for i in range(50)))
    return TblSource(str(p), schema(("a", Int64), ("c", Utf8)))


def test_raw_sql_through_cluster(tmp_path):
    src = _tbl(tmp_path)
    cluster = LocalCluster(num_executors=2, concurrent_tasks=2)
    try:
        ctx = BallistaContext.remote("localhost", cluster.port,
                                     **{"plan.server": "on"})
        ctx.register_source("t", src)
        df = ctx.sql(
            "select c, sum(a) as s, count(*) as n from t group by c order by c"
        )
        assert df._raw_sql is not None  # no client-side planning happened
        got = df.collect()
        a = np.arange(50)
        for i, k in enumerate(sorted({f"k{r}" for r in range(3)})):
            r = int(k[1:])
            m = a % 3 == r
            assert got["c"][i] == k
            assert int(got["s"][i]) == int(a[m].sum())
            assert int(got["n"][i]) == int(m.sum())
    finally:
        cluster.shutdown()


def _wait_failed(svc, job_id, timeout=10.0):
    import time

    deadline = time.time() + timeout
    while time.time() < deadline:
        st = svc.state.get_job_status(job_id)
        if st is not None and st.state == "failed":
            return st
        time.sleep(0.02)
    raise AssertionError("job never failed")


def test_raw_sql_unknown_table_fails_job_status(tmp_path):
    """SQL errors land in JobStatus('failed') like every other planning
    failure — not an opaque transport error."""
    svc = SchedulerService(SchedulerState(MemoryBackend()))
    params = pb.ExecuteQueryParams()
    params.sql = "select * from missing"
    job_id = svc.ExecuteQuery(params).job_id
    st = _wait_failed(svc, job_id)
    assert "missing" in (st.error or "")


def test_raw_sql_create_external_table_rejected(tmp_path):
    svc = SchedulerService(SchedulerState(MemoryBackend()))
    params = pb.ExecuteQueryParams()
    params.sql = ("create external table x (a bigint) "
                  "stored as csv location '/tmp/x'")
    job_id = svc.ExecuteQuery(params).job_id
    st = _wait_failed(svc, job_id)
    assert "client-side" in (st.error or "")


def test_get_file_metadata_parquet(tmp_path):
    """(reference parity: GetFileMetadata is Parquet-only schema/partition
    discovery, rust/scheduler/src/lib.rs:184-222)"""
    import pandas as pd

    p = tmp_path / "t"
    p.mkdir()
    pd.DataFrame({"a": [1, 2], "b": ["x", "y"]}).to_parquet(
        p / "part-0.parquet")
    pd.DataFrame({"a": [3], "b": ["z"]}).to_parquet(p / "part-1.parquet")

    svc = SchedulerService(SchedulerState(MemoryBackend()))
    res = svc.GetFileMetadata(pb.GetFileMetadataParams(
        path=str(p), file_type="parquet"))
    assert [f.name for f in res.schema.fields] == ["a", "b"]
    assert res.num_partitions == 2
    with pytest.raises(ClusterError, match="Parquet"):
        svc.GetFileMetadata(pb.GetFileMetadataParams(path=str(p),
                                                     file_type="csv"))


def test_raw_sql_frame_supports_dataframe_api(tmp_path):
    """A server-planned frame still answers schema()/count() by planning
    locally on demand, and DDL registers client-side under plan.server."""
    src = _tbl(tmp_path)
    cluster = LocalCluster(num_executors=1, concurrent_tasks=2)
    try:
        ctx = BallistaContext.remote("localhost", cluster.port,
                                     **{"plan.server": "on"})
        ctx.register_source("t", src)
        df = ctx.sql("select c, sum(a) as s from t group by c")
        assert df._raw_sql is not None
        assert list(df.schema().names()) == ["c", "s"]
        assert df.count() == 3

        # DDL goes through the client catalog even in plan.server mode
        p = tmp_path / "u.tbl"
        p.write_text("1|x|\n2|y|\n")
        ctx.sql(f"create external table u (a bigint, c varchar) "
                f"stored as tbl location '{p}'")
        got = ctx.sql("select count(*) as n from u").collect()
        assert int(got["n"][0]) == 2
    finally:
        cluster.shutdown()
