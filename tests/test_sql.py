"""SQL semantics regression tests (outer joins, NOT IN NULLs, subqueries)."""

import numpy as np
import pytest

from ballista_tpu import schema, Int64, Utf8
from ballista_tpu.client import BallistaContext


@pytest.fixture()
def ctx():
    c = BallistaContext.standalone()
    c.register_memtable(
        "cust", schema(("ckey", Int64), ("cname", Utf8)),
        {"ckey": [1, 2, 3], "cname": ["a", "b", "c"]}, primary_key="ckey",
    )
    c.register_memtable(
        "ords", schema(("okey", Int64), ("ockey", Int64), ("od", Int64)),
        {"okey": [10, 11, 12], "ockey": [1, 1, 2], "od": [5, 15, 25]},
        primary_key="okey",
    )
    return c


def test_left_join_where_filter_runs_post_join(ctx):
    # WHERE on the right table must eliminate null-extended rows
    out = ctx.sql(
        "select ckey, okey from cust left join ords on ckey = ockey "
        "where od >= 10 order by ckey, okey"
    ).collect()
    assert list(out["ckey"]) == [1, 2]
    assert list(out["okey"]) == [11, 12]
    # ON-clause filter keeps unmatched left rows (null-extended)
    out2 = ctx.sql(
        "select ckey, okey from cust left join ords on ckey = ockey "
        "and od >= 10 order by ckey"
    ).collect()
    assert list(out2["ckey"]) == [1, 2, 3]
    assert np.isnan(out2["okey"][2])


def test_right_join_preserves_right(ctx):
    out = ctx.sql(
        "select ckey, okey from ords right join cust on ockey = ckey "
        "order by ckey"
    ).collect()
    # every customer survives, incl. 3 with no order
    assert sorted(out["ckey"]) == [1, 1, 2, 3]


def test_not_in_subquery_null_semantics(ctx):
    ctx.register_memtable(
        "vals", schema(("v", Int64)), {"v": [1, 99]},
    )
    # no NULLs in the subquery: plain anti-join behavior
    out = ctx.sql(
        "select ckey from cust where ckey not in (select v from vals)"
    ).collect()
    assert sorted(out["ckey"]) == [2, 3]
    # NULL in the subquery output -> NOT IN never true -> empty
    out2 = ctx.sql(
        "select ckey from cust where ckey not in "
        "(select max(od) from ords where od > 100 group by okey)"
    ).collect()
    # subquery yields no rows at all here -> NOT IN over empty set is TRUE
    assert sorted(out2["ckey"]) == [1, 2, 3]


def test_not_in_subquery_with_actual_null(ctx, tmp_path):
    """A NULL value IN the subquery output empties NOT IN entirely.

    Regression: the optimizer's Join reconstructions dropped the
    null_aware flag, silently degrading NOT IN to a plain anti join."""
    p = tmp_path / "nv.tbl"
    p.write_text("1|x|\n|y|\n")  # second key is NULL
    from ballista_tpu.io import TblSource

    ctx.register_source(
        "nullvals", TblSource(str(p), schema(("k", Int64), ("s", Utf8)))
    )
    out = ctx.sql(
        "select ckey from cust where ckey not in (select k from nullvals)"
    ).collect()
    assert list(out["ckey"]) == []


def test_scalar_subquery_empty_is_null(ctx):
    out = ctx.sql(
        "select ckey from cust where ckey > "
        "(select od from ords where od > 1000)"
    ).collect()
    assert len(out) == 0  # NULL comparison is never true


def test_correlated_scalar_subquery(ctx):
    # customers whose smallest order date is < 10
    out = ctx.sql(
        "select ckey from cust where ckey = (select min(ockey) from ords "
        "where ockey = ckey) and 5 >= (select min(od) from ords "
        "where ockey = ckey) order by ckey"
    ).collect()
    assert list(out["ckey"]) == [1]


def test_factor_or_respects_qualifiers():
    # (n1.x='A' and n2.x='B') or (n1.x='B' and n2.x='A') must NOT collapse:
    # the qualifier distinguishes structurally identical display names
    from ballista_tpu import expr as ex
    from ballista_tpu.optimizer import factor_or

    n1x = ex.ColumnRef("x", "n1")
    n2x = ex.ColumnRef("x", "n2")
    b1 = ex.BinaryExpr(ex.BinaryExpr(n1x, "=", ex.lit("A")), "and",
                       ex.BinaryExpr(n2x, "=", ex.lit("B")))
    b2 = ex.BinaryExpr(ex.BinaryExpr(n1x, "=", ex.lit("B")), "and",
                       ex.BinaryExpr(n2x, "=", ex.lit("A")))
    out = factor_or(ex.BinaryExpr(b1, "or", b2))
    # nothing common: the OR survives intact as the first conjunct...
    assert out[0].name() == ex.BinaryExpr(b1, "or", b2).name()
    # ...plus IMPLIED per-column IN lists (every branch pins n1.x/n2.x to
    # a literal, so the OR implies membership — pushable to the scans,
    # the q7 shape)
    ins = {c.expr.relation: sorted(v.value for v in c.list)
           for c in out[1:]}
    assert ins == {"n1": ["A", "B"], "n2": ["A", "B"]}
    # and a genuinely common conjunct still factors
    common = ex.BinaryExpr(ex.ColumnRef("k", "t"), "=", ex.lit(1))
    c1 = ex.BinaryExpr(common, "and", ex.BinaryExpr(n1x, "=", ex.lit("A")))
    c2 = ex.BinaryExpr(common, "and", ex.BinaryExpr(n1x, "=", ex.lit("B")))
    out2 = factor_or(ex.BinaryExpr(c1, "or", c2))
    assert out2[0].name() == common.name()
    assert len(out2) == 3  # common + residual OR + implied n1.x IN (A,B)
