"""Mesh SPMD tests on the virtual 8-device CPU topology: distributed
two-phase aggregation and the ICI all_to_all hash shuffle."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax import lax

from ballista_tpu import schema, Int64, Decimal
from ballista_tpu.columnar import ColumnBatch
from ballista_tpu.kernels.aggregate import AggInput, grouped_aggregate
from ballista_tpu.parallel import make_mesh, MeshQueryRunner

N_DEV = 8
CAP = 256


def make_device_batches(seed=0):
    s = schema(("k", Int64), ("v", Int64))
    rng = np.random.default_rng(seed)
    batches = []
    all_k, all_v = [], []
    for d in range(N_DEV):
        n = int(rng.integers(CAP // 2, CAP))
        k = rng.integers(0, 5, n)
        v = rng.integers(0, 100, n)
        all_k.append(k)
        all_v.append(v)
        batches.append(
            ColumnBatch.from_numpy(s, {"k": k, "v": v}, capacity=CAP)
        )
    return s, batches, np.concatenate(all_k), np.concatenate(all_v)


def test_mesh_two_phase_aggregate(eight_devices):
    s, batches, gk, gv = make_device_batches()
    mesh = make_mesh(N_DEV)
    runner = MeshQueryRunner(mesh)
    G = 8

    def device_fn(cols, live):
        # partial aggregate on this device
        res = grouped_aggregate(
            [cols["k"]], live,
            [AggInput("sum", cols["v"], None), AggInput("count", None, None)],
            G,
        )
        keys = jnp.where(res.group_valid,
                         jnp.take(cols["k"], res.rep_indices), -1)
        # merge: all_gather partial tables, re-aggregate (replicated)
        keys_g = lax.all_gather(keys, "data").reshape(-1)
        sums_g = lax.all_gather(res.aggregates[0], "data").reshape(-1)
        cnts_g = lax.all_gather(res.aggregates[1], "data").reshape(-1)
        live_g = keys_g >= 0
        final = grouped_aggregate(
            [keys_g], live_g,
            [AggInput("sum", sums_g, None), AggInput("sum", cnts_g, None)],
            G,
        )
        fk = jnp.where(final.group_valid, jnp.take(keys_g, final.rep_indices), -1)
        return fk, final.aggregates[0], final.aggregates[1]

    (fk, fs, fc), _ = runner.run_spmd(s, batches, device_fn)
    fk, fs, fc = np.asarray(fk), np.asarray(fs), np.asarray(fc)
    got = {int(k): (int(s_), int(c)) for k, s_, c in zip(fk, fs, fc) if k >= 0}

    exp = {}
    for k in np.unique(gk):
        m = gk == k
        exp[int(k)] = (int(gv[m].sum()), int(m.sum()))
    assert got == exp


def test_mesh_all_to_all_shuffle(eight_devices):
    s, batches, gk, gv = make_device_batches(seed=1)
    mesh = make_mesh(N_DEV)
    runner = MeshQueryRunner(mesh)
    shuffle = runner.shuffle_fn("k", dest_capacity=CAP)

    def device_fn(cols, live):
        cols2, live2, overflowed = shuffle(cols, live)
        # after the shuffle every live row on this device must hash here;
        # verify by computing destination again and summing local stats
        from ballista_tpu.kernels.mesh_shuffle import destination_ids

        dest2 = destination_ids(cols2["k"], live2, N_DEV)
        me = lax.axis_index("data")
        misplaced = jnp.sum(
            jnp.logical_and(live2, dest2 != me).astype(jnp.int32)
        )
        local_sum = jnp.sum(jnp.where(live2, cols2["v"], 0))
        local_rows = jnp.sum(live2.astype(jnp.int64))
        return (
            lax.all_gather(misplaced, "data"),
            lax.all_gather(local_sum, "data"),
            lax.all_gather(local_rows, "data"),
        )

    (mis, sums, rows), _ = runner.run_spmd(s, batches, device_fn)
    assert int(np.asarray(mis).sum()) == 0, "rows landed on wrong device"
    assert int(np.asarray(rows).sum()) == len(gk), "rows lost in shuffle"
    assert int(np.asarray(sums).sum()) == int(gv.sum()), "values corrupted"
