"""Compile governor (PR 3): shape-bucket ladder, unified jit cache,
compile observability, prewarm.

Layers, bottom-up: ladder math + knobs; governor entry
sharing/attribution/eviction units; the partition-size-jitter pin (same
plan over N distinct row counts compiles at most once per ladder rung,
not once per count); the adaptive-re-plan regression (a re-built plan
performs ZERO new compiles for unchanged signatures — the per-instance
``self._jit_*`` dicts this PR deleted used to throw every trace away);
a masked-correctness sweep (bucket-padded results row-identical to
unpadded across agg/sort/join/limit); prewarm smoke; and the
``dev/check_jit_sites.py`` lint so the scattered-cache problem can't
regrow. Also hosts the BALLISTA_XLA_CACHE_MIN_COMPILE_SECS default pin.
"""

import os
import sys

import numpy as np
import pytest

from ballista_tpu import Int64, Utf8, col, lit, schema
from ballista_tpu.client import BallistaContext
from ballista_tpu.compile import (
    bucket_capacity,
    bucket_ladder,
    compile_stats,
    governed,
    governor,
    reconfigure,
)


@pytest.fixture
def bucket_env(monkeypatch):
    """Set BALLISTA_SHAPE_BUCKETS* env for a test and re-read it,
    restoring the default config afterwards."""

    def set_env(**kv):
        for k, v in kv.items():
            name = "BALLISTA_SHAPE_BUCKETS" + (f"_{k.upper()}" if k else "")
            monkeypatch.setenv(name, str(v))
        reconfigure()

    yield set_env
    monkeypatch.undo()
    reconfigure()


# ---------------------------------------------------------------------------
# ladder math + knobs
# ---------------------------------------------------------------------------


def test_bucket_ladder_defaults():
    assert bucket_capacity(0) == 1024  # floor
    assert bucket_capacity(1) == 1024
    assert bucket_capacity(1024) == 1024
    assert bucket_capacity(1025) == 2048
    assert bucket_capacity(6_001_215) == 1 << 23
    assert bucket_ladder(100_000) == [1024, 2048, 4096, 8192, 16384,
                                      32768, 65536, 131072]


def test_bucket_knobs(bucket_env):
    bucket_env(floor=4096, growth=4)
    assert bucket_capacity(10) == 4096
    assert bucket_capacity(5000) == 16384
    assert bucket_ladder(100_000) == [4096, 16384, 65536, 262144]
    # non-power-of-two knobs snap up
    bucket_env(floor=1000, growth=3)
    assert bucket_capacity(10) == 1024
    assert bucket_capacity(2000) == 4096  # growth 3 -> 4


def test_buckets_off_is_exact_pow2(bucket_env):
    bucket_env(**{"": "off"})
    assert bucket_capacity(10) == 16
    assert bucket_capacity(600) == 1024
    assert bucket_capacity(3) == 8  # minimum still holds


# ---------------------------------------------------------------------------
# governor units
# ---------------------------------------------------------------------------


def test_governed_entry_shared_and_counted():
    import jax.numpy as jnp

    built = []

    def build():
        built.append(1)
        return lambda x: x + 1

    key = ("test.unit", "shared")
    f1 = governed(key, build)
    f2 = governed(key, build)
    assert f1 is f2
    assert built == [1]  # second lookup did not rebuild
    out = f1(jnp.asarray(1))
    assert int(out) == 2
    assert f1.calls >= 1


def test_governed_namespace_eviction():
    gov = governor()
    gov.clear("test.evict")
    for i in range(5):
        governed(("test.evict", i), lambda: (lambda x: x), cap=3)
    assert gov.namespace_sizes().get("test.evict") == 3
    gov.clear("test.evict")


def test_governed_build_may_request_governed_entries():
    """Deadlock regression: a build() that itself asks the governor for
    another entry (mesh SPMD programs wrap an aggregate's grouped
    kernel) must not self-deadlock — entries build outside the lock."""
    import jax.numpy as jnp

    gov = governor()
    gov.clear("test.nested")

    def inner_build():
        return lambda x: x * 2

    def outer_build():
        inner = governed(("test.nested", "inner"), inner_build)
        return lambda x: inner(x) + 1

    out = governed(("test.nested", "outer"), outer_build)(jnp.asarray(3))
    assert int(out) == 7
    gov.clear("test.nested")


def test_governed_compile_attribution_to_metrics():
    import jax.numpy as jnp

    from ballista_tpu.observability.metrics import MetricsSet

    m = MetricsSet()
    # a fresh closure constant guarantees a fresh XLA program
    fn = governed(("test.unit", "attrib"),
                  lambda: (lambda x: x * 3 + 17), metrics=m)
    fn(jnp.arange(1024))
    vals = m.values()
    assert vals.get("compile_count", 0) >= 1
    assert vals.get("elapsed_compile", 0.0) > 0.0
    st = compile_stats()
    assert st["backend_compiles"] >= 1
    assert st["entries"] >= 1


# ---------------------------------------------------------------------------
# partition-size jitter: compiles bounded by the ladder, not the counts
# ---------------------------------------------------------------------------


def _jitter_ctx(n: int) -> BallistaContext:
    s = schema(("k", Int64), ("v", Int64))
    ctx = BallistaContext.standalone()
    ctx.register_memtable("t", s, {
        "k": (np.arange(n) % 7).astype(np.int64),
        "v": np.arange(n, dtype=np.int64),
    })
    return ctx


_JITTER_SQL = ("SELECT k, SUM(v) AS sv, COUNT(*) AS c FROM t "
               "GROUP BY k ORDER BY k")


def _expected(n: int):
    k = (np.arange(n) % 7).astype(np.int64)
    v = np.arange(n, dtype=np.int64)
    return {int(g): (int(v[k == g].sum()), int((k == g).sum()))
            for g in range(7)}


def _compile_requests() -> int:
    """backend compiles + persistent-disk-cache hits: every compile
    REQUEST, whether or not the disk cache absorbed it. A recompile
    served from disk still means the in-memory trace was not reused."""
    st = compile_stats()
    return int(st["backend_compiles"]) + int(st["persistent_cache_hits"])


def test_partition_size_jitter_bounded_by_ladder():
    """N distinct row counts -> compiles happen only when a NEW ladder
    rung is first seen; re-running at other counts on a known rung
    performs zero compile requests (fresh context + fresh operator
    instances every time). The counts are chosen to round to DIFFERENT
    power-of-two capacities (128/512/1024), so per-exact-shape caching —
    the pre-governor behavior — fails this test."""
    counts_rung1 = [100, 300, 600, 1000]   # all bucket to the 1024 floor
    counts_rung2 = [1500, 1800, 2048]      # all bucket to 2048
    assert {bucket_capacity(n) for n in counts_rung1} == {1024}
    assert {bucket_capacity(n) for n in counts_rung2} == {2048}

    def run(n):
        ctx = _jitter_ctx(n)
        out = ctx.sql(_JITTER_SQL).collect()
        exp = _expected(n)
        got = {int(r.k): (int(r.sv), int(r.c)) for r in out.itertuples()}
        assert got == exp

    run(counts_rung1[0])  # first sight of rung 1024: compiles allowed
    base = _compile_requests()
    for n in counts_rung1[1:]:
        run(n)
    assert _compile_requests() == base, \
        "distinct row counts on one ladder rung must not recompile"
    run(counts_rung2[0])  # first sight of rung 2048: compiles allowed
    base2 = _compile_requests()
    for n in counts_rung2[1:]:
        run(n)
    assert _compile_requests() == base2


# ---------------------------------------------------------------------------
# re-plan regression: new operator instances reuse every governed trace
# ---------------------------------------------------------------------------


def _replan_ctx() -> BallistaContext:
    ctx = BallistaContext.standalone()
    n = 1200
    rng = np.random.RandomState(7)
    ctx.register_memtable("orders_r", schema(
        ("okey", Int64), ("ckey", Int64), ("amount", Int64)), {
        "okey": np.arange(n, dtype=np.int64),
        "ckey": rng.randint(0, 40, n).astype(np.int64),
        "amount": rng.randint(0, 1000, n).astype(np.int64),
    })
    ctx.register_memtable("cust_r", schema(
        ("ckey", Int64), ("name", Utf8)), {
        "ckey": np.arange(40, dtype=np.int64),
        "name": [f"c{i % 5}" for i in range(40)],
    })
    return ctx


_REPLAN_SQL = (
    "SELECT name, COUNT(*) AS n, SUM(amount) AS amt "
    "FROM orders_r JOIN cust_r ON orders_r.ckey = cust_r.ckey "
    "WHERE amount > 100 GROUP BY name ORDER BY name"
)


def test_replan_performs_zero_new_compiles():
    """The satellite regression: re-planning (fresh physical operator
    instances over the same logical plan — what adaptive execution does
    on every stage completion) must hit the governor for every kernel.
    The old per-instance ``_jit_probe`` / ``_jit_cache`` dicts leaked
    exactly here."""
    ctx = _replan_ctx()
    first = ctx.sql(_REPLAN_SQL).collect()
    # fresh DataFrame -> plan_logical runs again -> ALL-NEW operator
    # instances (same signatures)
    ctx._plan_cache.clear()
    before = _compile_requests()
    second = ctx.sql(_REPLAN_SQL).collect()
    after = _compile_requests()
    assert after == before, (
        f"re-planned query issued {after - before} new compile "
        "requests; unchanged signatures must reuse governed entries")
    assert first.equals(second)


def test_governed_entries_do_not_pin_plans():
    """Memory regression: governed closures capture config-only trace
    twins, never the live operators — else the process-wide cache would
    pin plan subtrees (cached scan batches, join build-side device
    buffers) until LRU eviction."""
    import gc
    import weakref

    ctx = _replan_ctx()
    df = ctx.sql(_REPLAN_SQL)
    df.collect()
    refs = []

    def walk(n):
        refs.append(weakref.ref(n))
        for c in n.children():
            walk(c)

    walk(df._phys)
    assert refs
    del df, ctx
    gc.collect()
    alive = [r() for r in refs if r() is not None]
    assert not alive, (
        f"{len(alive)} operator(s) still pinned after the plan died: "
        f"{[type(a).__name__ for a in alive]}")


# ---------------------------------------------------------------------------
# masked correctness: bucket padding is row-identical to exact shapes
# ---------------------------------------------------------------------------


def _sweep_ctx() -> BallistaContext:
    ctx = BallistaContext.standalone()
    n = 1337  # deliberately off-rung
    rng = np.random.RandomState(3)
    amount = rng.randint(-50, 1000, n).astype(np.int64)
    valid = rng.rand(n) > 0.1  # ~10% NULLs through the agg paths
    ctx.register_memtable("fact_s", schema(
        ("id", Int64), ("grp", Utf8), ("dkey", Int64),
        ("amount", Int64)), {
        "id": np.arange(n, dtype=np.int64),
        "grp": [f"g{i % 11}" for i in range(n)],
        "dkey": rng.randint(0, 23, n).astype(np.int64),
        "amount": amount,
    })
    # dim table sized 23 (tiny, well under the floor)
    ctx.register_memtable("dim_s", schema(
        ("dkey", Int64), ("label", Utf8)), {
        "dkey": np.arange(23, dtype=np.int64),
        "label": [f"l{i % 4}" for i in range(23)],
    })
    return ctx


_SWEEP_SQLS = [
    # aggregate (grouped, utf8 + int keys)
    "SELECT grp, COUNT(*) AS n, SUM(amount) AS s, MIN(amount) AS mn, "
    "MAX(amount) AS mx FROM fact_s GROUP BY grp ORDER BY grp",
    # scalar aggregate
    "SELECT COUNT(*) AS n, SUM(amount) AS s FROM fact_s",
    # join + aggregate
    "SELECT label, COUNT(*) AS n, SUM(amount) AS s FROM fact_s "
    "JOIN dim_s ON fact_s.dkey = dim_s.dkey GROUP BY label ORDER BY label",
    # filter + sort + limit
    "SELECT id, amount FROM fact_s WHERE amount > 500 "
    "ORDER BY amount DESC, id LIMIT 17",
    # semi-ish subquery shape
    "SELECT COUNT(*) AS n FROM fact_s WHERE dkey IN "
    "(SELECT dkey FROM dim_s WHERE label = 'l1')",
]


def test_masked_correctness_bucket_on_vs_off(bucket_env):
    got_on = []
    for q in _SWEEP_SQLS:  # default: buckets on
        got_on.append(_sweep_ctx().sql(q).collect())
    bucket_env(**{"": "off"})
    for q, on in zip(_SWEEP_SQLS, got_on):
        off = _sweep_ctx().sql(q).collect()
        assert on.equals(off), f"bucketed result differs for: {q}"


def test_bucketed_batch_padding_is_dead():
    """Entry-boundary pin: from_numpy pads to the ladder rung and the
    padding rows are unselected, invisible to collect."""
    from ballista_tpu.columnar import ColumnBatch

    s = schema(("a", Int64))
    b = ColumnBatch.from_numpy(s, {"a": np.arange(37, dtype=np.int64)})
    assert b.capacity == bucket_capacity(37)
    assert int(b.num_rows) == 37
    out = b.to_pydict()
    assert list(out["a"]) == list(range(37))


# ---------------------------------------------------------------------------
# observability surfaces
# ---------------------------------------------------------------------------


def test_compile_metrics_reach_explain_analyze():
    ctx = BallistaContext.standalone()
    n = 900
    # a schema unique to this test guarantees fresh signatures -> at
    # least one real compile lands inside the ANALYZE window
    ctx.register_memtable("ea_compile_t", schema(
        ("ea_k", Int64), ("ea_v", Int64)), {
        "ea_k": (np.arange(n) % 5).astype(np.int64),
        "ea_v": np.arange(n, dtype=np.int64),
    })
    out = ctx.sql(
        "EXPLAIN ANALYZE SELECT ea_k, SUM(ea_v) AS s FROM ea_compile_t "
        "WHERE ea_v > 13 GROUP BY ea_k ORDER BY ea_k").collect()
    text = dict(zip(out["plan_type"], out["plan"]))["plan_with_metrics"]
    assert "compile_count=" in text
    assert "elapsed_compile=" in text


def test_trace_span_emitted_for_compiles(tmp_path, monkeypatch):
    import json

    from ballista_tpu.observability import tracing

    trace_file = tmp_path / "trace.jsonl"
    monkeypatch.setenv("BALLISTA_TRACE", "1")
    monkeypatch.setenv("BALLISTA_TRACE_FILE", str(trace_file))
    tracing.reconfigure()
    try:
        import jax.numpy as jnp

        fn = governed(("test.unit", "traced"),
                      lambda: (lambda x: x * 5 - 2))
        fn(jnp.arange(512))
    finally:
        monkeypatch.undo()
        tracing.reconfigure()
    lines = [json.loads(l) for l in trace_file.read_text().splitlines()]
    spans = [l for l in lines if l["name"] == "compile.jit"]
    assert spans and spans[0]["compiles"] >= 1
    assert "test.unit" in spans[0]["key"]


def test_persistent_cache_min_compile_secs_defaults_to_zero():
    import jax

    # ballista_tpu/__init__.py only configures the cache when the dir is
    # writable; when it did, the knob default must be 0 (cache EVERY
    # kernel — the 0.1s floor silently excluded small ones)
    if jax.config.jax_compilation_cache_dir:
        assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.0
        assert os.environ.get("BALLISTA_XLA_CACHE_MIN_COMPILE_SECS") is None


# ---------------------------------------------------------------------------
# prewarm
# ---------------------------------------------------------------------------


def test_prewarm_compiles_scan_chain(tmp_path, monkeypatch):
    from ballista_tpu.compile import maybe_prewarm
    from ballista_tpu.compile.governor import _STATS
    from ballista_tpu.execution import collect_physical, plan_logical

    n = 1100
    lines = "".join(f"{i}|{i * 3}|\n" for i in range(n))
    (tmp_path / "t.tbl").write_text(lines)
    ctx = BallistaContext.standalone()
    ctx.register_tbl("pw_t", str(tmp_path / "t.tbl"),
                     schema(("pk", Int64), ("pv", Int64)))
    df = ctx.sql("SELECT pk, pv FROM pw_t WHERE pv > 100")
    phys = plan_logical(df.plan)
    monkeypatch.setenv("BALLISTA_PREWARM", "1")
    before = _STATS["prewarm_compiles"]
    t = maybe_prewarm(phys)
    assert t is not None
    t.join(timeout=120)
    assert not t.is_alive()
    assert _STATS["prewarm_compiles"] > before
    # second call on the same plan is a no-op
    assert maybe_prewarm(phys) is None
    out = collect_physical(phys)
    assert sorted(out["pk"]) == [i for i in range(n) if i * 3 > 100]


def test_prewarm_disabled_by_default(monkeypatch):
    from ballista_tpu.compile import maybe_prewarm, prewarm_enabled

    monkeypatch.delenv("BALLISTA_PREWARM", raising=False)
    assert not prewarm_enabled()
    assert maybe_prewarm(object()) is None


# ---------------------------------------------------------------------------
# lint: no raw jax.jit outside ballista_tpu/compile/
# ---------------------------------------------------------------------------


def test_no_raw_jit_sites_outside_compile():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "dev"))
    try:
        import check_jit_sites
    finally:
        sys.path.pop(0)
    hits = check_jit_sites.scan()
    assert hits == [], "\n".join(f"{r}:{i}: {l}" for r, i, l in hits)
