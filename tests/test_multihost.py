"""Multi-host SPMD: the engine's mesh shuffle-aggregation spanning
PROCESS boundaries (2 processes x 2 virtual devices = one 4-device
global mesh; collectives cross processes over the jax.distributed
runtime — the DCN analogue the SURVEY maps the reference's
cross-host Flight shuffle onto).

Heavier than a unit test (spawns subprocesses that handshake on a
coordinator port), so it asserts the full path: per-process scan
partitions -> local slot layout -> global stacked array ->
lax.all_to_all row exchange ACROSS processes -> per-device final
aggregation -> replicated result, matched against a host oracle.
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    p = s.getsockname()[1]
    s.close()
    return p


_WORKER = textwrap.dedent("""
    import os, sys, json
    pid = int(sys.argv[1]); nprocs = int(sys.argv[2]); port = sys.argv[3]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)
    sys.path.insert(0, "__REPO__")
    import jax
    jax.config.update("jax_platforms", "cpu")
    from ballista_tpu.parallel import multihost

    multihost.init_group(f"localhost:{port}", nprocs, pid,
                         local_device_count=2)
    import jax.numpy as jnp
    import numpy as np
    from functools import partial
    from jax.sharding import PartitionSpec as P
    from ballista_tpu.parallel.mesh import shard_map
    from ballista_tpu.kernels import mesh_shuffle
    from ballista_tpu.kernels.aggregate import AggInput, grouped_aggregate

    mesh = multihost.global_mesh()
    n_dev = mesh.devices.size
    assert n_dev == 2 * nprocs, f"global mesh saw {n_dev} devices"

    # deterministic per-SLOT data (every process computes all slots'
    # data for the oracle, but only materializes its local ones)
    CAP, G = 64, 7
    def slot_rows(slot):
        rng = np.random.default_rng(100 + slot)
        keys = rng.integers(0, G, CAP).astype(np.int64)
        vals = rng.integers(0, 1000, CAP).astype(np.int64)
        live = rng.random(CAP) < 0.8
        return keys, vals, live

    local = multihost.local_slot_range(mesh)
    slot_batches = []
    for slot in local:
        k, v, l = slot_rows(slot)
        slot_batches.append((jnp.asarray(k), jnp.asarray(v),
                             jnp.asarray(l)))
    stacked = multihost.stack_local_to_global(slot_batches, mesh)

    @partial(shard_map, mesh=mesh, in_specs=(P("data"),), out_specs=P(),
             check_vma=False)
    def run(st):
        k, v, live = jax.tree.map(lambda x: x[0], st)
        dest = mesh_shuffle.destination_ids(k, live, n_dev)
        (k2, v2), live2, _ = mesh_shuffle.all_to_all_rows(
            [k, v], live, dest, "data", n_dev, CAP)
        res = grouped_aggregate([k2], live2,
                                [AggInput("sum", v2, None),
                                 AggInput("count", None, None)], 8)
        keys_out = jnp.where(res.group_valid,
                             jnp.take(k2, res.rep_indices), -1)
        # replicated output: every process sees the full result
        return (jax.lax.all_gather(keys_out, "data").reshape(-1),
                jax.lax.all_gather(res.aggregates[0], "data").reshape(-1),
                jax.lax.all_gather(res.aggregates[1], "data").reshape(-1))

    keys, sums, counts = jax.jit(run)(stacked)
    got = {int(k): (int(s), int(c))
           for k, s, c in zip(np.asarray(keys), np.asarray(sums),
                              np.asarray(counts)) if k >= 0}

    exp = {}
    for slot in range(n_dev):
        k, v, l = slot_rows(slot)
        for g in range(G):
            m = l & (k == g)
            if m.any():
                s0, c0 = exp.get(g, (0, 0))
                exp[g] = (s0 + int(v[m].sum()), c0 + int(m.sum()))
    assert got == exp, f"p{pid}: {got} != {exp}"
    print(f"MULTIHOST_OK p{pid} groups={len(got)}", flush=True)
""")


@pytest.mark.sf02  # heavyweight: spawns a process group
def test_cross_process_mesh_shuffle_aggregation(tmp_path):
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    port = _free_port()
    nprocs = 2
    script = _WORKER.replace("__REPO__", repo)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    from tests.procutil import spawn_script

    # drained spawns: either worker can exceed the OS pipe buffer with
    # XLA warning spam, and a worker blocked on a pipe write stalls the
    # whole collective (both processes are in the same all_to_all)
    procs = [
        spawn_script(["-c", script, str(i), str(nprocs), str(port)], env)
        for i in range(nprocs)
    ]
    try:
        for p in procs:
            p.wait_exit(timeout=180)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, p in enumerate(procs):
        out = p.text
        assert p.popen.returncode == 0, f"process {i} failed:\n{out[-2000:]}"
        assert f"MULTIHOST_OK p{i}" in out, out[-2000:]
