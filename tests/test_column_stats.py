"""ColumnStats: per-column selectivity stats computed at shuffle write
and carried through proto into PartitionLocations.

The reference DECLARES ColumnStats min/max/null/distinct
(rust/core/proto/ballista.proto:478-485) but never populates it; here the
write path fills it (io/ipc.py) and the scheduling metadata carries it,
so the optimizer has real numbers to consume.
"""

import numpy as np
import pytest

from ballista_tpu import Date32, Decimal, Int64, Utf8, schema
from ballista_tpu.columnar import ColumnBatch
from ballista_tpu.io import ipc
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu import serde


def _stats_by_name(stats):
    return {c["name"]: c for c in stats["columns"]}


def test_write_partition_computes_column_stats(tmp_path):
    import jax.numpy as jnp

    s = schema(("a", Int64), ("k", Utf8), ("d", Date32), ("p", Decimal(2)))
    days = (np.array(["1995-01-10", "1994-06-01", "1996-03-03",
                      "1995-12-31"], dtype="datetime64[D]")
            - np.datetime64("1970-01-01")).astype(np.int32)
    b = ColumnBatch.from_pydict(s, {
        "a": [5, -3, 12, 7],
        "k": ["pear", "apple", "fig", "apple"],
        "d": days,
        "p": [1.25, 99.5, -2.75, 0.0],
    })
    # null out one 'a' row
    col = b.columns[0]
    validity = np.ones(b.capacity, bool)
    validity[1] = False
    b.columns = (type(col)(col.values, col.dtype,
                           jnp.asarray(validity), col.dictionary),
                 ) + b.columns[1:]

    path = str(tmp_path / "part.arrow")
    stats = ipc.write_partition(path, [b])
    cols = _stats_by_name(stats)

    assert cols["a"]["null_count"] == 1
    assert cols["a"]["min"] == -3 or cols["a"]["min"] == 5  # null excluded
    assert cols["a"]["max"] == 12
    assert cols["k"]["min"] == "apple" and cols["k"]["max"] == "pear"
    assert cols["k"]["distinct_count"] == 3
    # dates carried as epoch days (physical repr)
    d0 = np.datetime64("1994-06-01") - np.datetime64("1970-01-01")
    assert cols["d"]["min"] == int(d0 / np.timedelta64(1, "D"))
    # decimals carried as scaled ints
    assert cols["p"]["min"] == -275 and cols["p"]["max"] == 9950


def test_column_stats_proto_roundtrip():
    stats = {
        "num_rows": 10, "num_batches": 1, "num_bytes": 1234,
        "columns": [
            {"name": "a", "null_count": 2, "distinct_count": -1,
             "min": -7, "max": 99},
            {"name": "k", "null_count": 0, "distinct_count": 4,
             "min": "aa", "max": "zz"},
            {"name": "f", "null_count": 0, "distinct_count": -1,
             "min": -1.5, "max": 2.25},
        ],
    }
    msg = pb.PartitionStats()
    serde.stats_to_proto(stats, msg)
    back = serde.stats_from_proto(msg)
    assert back == stats


def test_cluster_locations_carry_column_stats(tmp_path):
    """End to end: a cluster query's completed-task locations expose the
    per-column stats the executor computed at write time."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.distributed.executor import LocalCluster
    from ballista_tpu.io import TblSource

    d = tmp_path / "t"
    d.mkdir()
    (d / "p0.tbl").write_text(
        "".join(f"{i}|grp{i % 3}|\n" for i in range(50)))
    cluster = LocalCluster(num_executors=1, concurrent_tasks=2)
    try:
        ctx = BallistaContext.remote("localhost", cluster.port,
                                     **{"agg.partitions": "2"})
        ctx.register_source(
            "t", TblSource(str(d), schema(("a", Int64), ("k", Utf8))))
        ctx.sql("select k, sum(a) as s from t group by k").collect()

        # some completed stage carries per-column stats incl. exact
        # min/max of the written shuffle data
        found = []
        for job_key, _ in cluster.state.kv.get_from_prefix(
                f"/ballista/{cluster.state.ns}/jobs/"):
            job_id = job_key.rsplit("/", 1)[-1]
            locs = cluster.state.stage_locations(job_id)
            for stage_locs in locs.values():
                for loc in stage_locs:
                    for c in (loc.stats or {}).get("columns", []) or []:
                        found.append(c)
        assert found, "no column stats in any partition location"
        assert any("min" in c and "max" in c for c in found)
    finally:
        cluster.shutdown()
