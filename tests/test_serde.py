"""Plan/expression proto roundtrip tests.

Models the reference's strongest suite (reference:
rust/core/src/serde/logical_plan/mod.rs:20-920 — 25 roundtrip tests
comparing debug strings after proto->plan->proto)."""

import datetime as dt

import pytest

from ballista_tpu import (
    schema, col, lit, date_lit, sum_, avg, min_, max_, count,
    Int32, Int64, Decimal, Utf8, Date32, Boolean, Float64,
)
from ballista_tpu import expr as ex
from ballista_tpu import serde
from ballista_tpu.io import CsvSource, TblSource
from ballista_tpu.logical import LogicalPlanBuilder, TableScan


@pytest.fixture(scope="module")
def tbl_source(tmp_path_factory):
    p = tmp_path_factory.mktemp("serde") / "t.tbl"
    p.write_text("1|2.50|x|1995-01-01|\n2|3.75|y|1996-06-15|\n")
    s = schema(("a", Int64), ("b", Decimal(2)), ("c", Utf8), ("d", Date32))
    return TblSource(str(p), s)


EXPRS = [
    col("a"),
    ex.ColumnRef("x", "t"),
    lit(42),
    lit(1.5),
    lit("hello"),
    lit(True),
    lit(None),
    date_lit("1998-09-02"),
    ex.Literal(12345, Decimal(2)),
    (col("a") + lit(1)) * col("b"),
    (col("a") >= lit(10)) & ~(col("c") == lit("x")),
    col("a").is_null(),
    col("a").is_not_null(),
    col("b").alias("renamed"),
    ex.Cast(col("a"), Decimal(4)),
    ex.InList(col("c"), [lit("p"), lit("q")], negated=True),
    ex.Like(col("c"), "%foo_", negated=False),
    ex.case().when(col("a") == lit(1), lit("one")).otherwise(lit("many")),
    ex.Case(col("a"), [(lit(1), lit(10))], None),
    ex.ScalarFunction("sqrt", [col("b")]),
    ex.ScalarFunction("substr", [col("c"), lit(1), lit(2)]),
    sum_(col("b")),
    avg(col("b")),
    min_(col("a")),
    max_(col("a")),
    count(),
    count(col("c")),
    ex.SortExpr(col("a"), ascending=False, nulls_first=True),
]


@pytest.mark.parametrize("e", EXPRS, ids=lambda e: e.name()[:40])
def test_expr_roundtrip(e):
    p = serde.expr_to_proto(e)
    e2 = serde.expr_from_proto(p)
    assert e2.name() == e.name()
    # double roundtrip must be byte-stable
    assert serde.expr_to_proto(e2).SerializeToString() == p.SerializeToString()


def plans(src):
    b = LogicalPlanBuilder.scan("t", src)
    return [
        b.build(),
        b.filter((col("a") > lit(1)) & (col("d") < date_lit("1996-01-01"))).build(),
        b.project([col("a"), (col("b") * lit(2)).alias("bb")]).build(),
        b.aggregate([col("c")], [sum_(col("b")).alias("s"), count().alias("n")]).build(),
        b.sort([ex.SortExpr(col("b"), ascending=False)]).limit(5).build(),
        b.repartition(4, [col("a")]).build(),
        b.join(LogicalPlanBuilder.scan("t2", src), on=[("a", "a")], how="left").build(),
    ]


def test_plan_roundtrips(tbl_source):
    for plan in plans(tbl_source):
        p = serde.plan_to_proto(plan)
        plan2 = serde.plan_from_proto(p)
        assert plan2.pretty() == plan.pretty()
        assert plan2.schema() == plan.schema()
        assert serde.plan_to_proto(plan2).SerializeToString() == p.SerializeToString()


def test_physical_plan_roundtrip(tbl_source):
    from ballista_tpu.execution import plan_logical
    from ballista_tpu import serde as sd

    plan = (
        LogicalPlanBuilder.scan("t", tbl_source)
        .filter(col("a") > lit(0))
        .aggregate([col("c")], [sum_(col("b")).alias("s")])
        .sort([ex.SortExpr(col("s"), ascending=False)])
        .limit(3)
        .build()
    )
    phys = plan_logical(plan)
    p = sd.physical_to_proto(phys)
    phys2 = sd.physical_from_proto(p)
    assert phys2.pretty() == phys.pretty()
    assert sd.physical_to_proto(phys2).SerializeToString() == p.SerializeToString()


def test_physical_roundtrip_executes(tbl_source):
    """Deserialized physical plans must actually run (the executor path)."""
    from ballista_tpu.execution import collect_physical, plan_logical

    plan = (
        LogicalPlanBuilder.scan("t", tbl_source)
        .aggregate([], [sum_(col("b")).alias("s"), count().alias("n")])
        .build()
    )
    phys = plan_logical(plan)
    phys2 = serde.physical_from_proto(serde.physical_to_proto(phys))
    out = collect_physical(phys2)
    assert float(out["s"][0]) == pytest.approx(6.25)
    assert int(out["n"][0]) == 2
