"""TPC-H integration tests: SQL text -> engine results vs pandas oracle.

The engine-level equivalent of the reference's docker-compose TPC-H
integration run (reference: dev/integration-tests.sh:1-11, query set
q1,q3,q5,q6,q10,q12 from rust/benchmarks/tpch/run.sh:6-9) — but with
programmatic golden assertions instead of eyeballing."""

import os

import numpy as np
import pandas as pd
import pytest

from benchmarks.tpch import datagen, oracle
from benchmarks.tpch.schema_def import register_tpch

# the reference's integration set is q1,q3,q5,q6,q10,q12
# (rust/benchmarks/tpch/run.sh:6-9); we assert a much wider set
QUERIES = ["q1", "q2", "q3", "q4", "q5", "q6", "q7", "q8", "q9", "q10",
           "q11", "q12", "q13", "q14", "q15", "q16", "q17", "q18", "q19",
           "q20", "q21", "q22"]
QDIR = os.path.join(os.path.dirname(__file__), "..", "benchmarks", "tpch", "queries")


@pytest.fixture(scope="session")
def tpch(tmp_path_factory):
    data_dir = str(tmp_path_factory.mktemp("tpch_data"))
    datagen.generate(data_dir, scale=0.002, num_parts=2)
    from ballista_tpu.client import BallistaContext

    ctx = BallistaContext.standalone()
    register_tpch(ctx, data_dir, "tbl")
    tables = oracle.load_tables(data_dir)
    return ctx, tables


def normalize(df: pd.DataFrame) -> pd.DataFrame:
    out = df.copy()
    for c in out.columns:
        if out[c].dtype.kind == "M":
            out[c] = out[c].values.astype("datetime64[D]")
    return out.reset_index(drop=True)


@pytest.mark.parametrize("qname", QUERIES)
def test_tpch_query(tpch, qname):
    ctx, tables = tpch
    sql = open(os.path.join(QDIR, f"{qname}.sql")).read()
    got = normalize(ctx.sql(sql).collect())
    exp = normalize(oracle.ORACLES[qname](tables))

    assert list(got.columns) == list(exp.columns), (got.columns, exp.columns)
    assert len(got) == len(exp), f"{qname}: {len(got)} rows vs {len(exp)}"
    for c in exp.columns:
        g, e = got[c], exp[c]
        if e.dtype.kind in "fc":
            np.testing.assert_allclose(
                g.astype(float), e.astype(float), rtol=1e-6, atol=1e-6,
                err_msg=f"{qname}.{c}",
            )
        else:
            np.testing.assert_array_equal(
                g.to_numpy(), e.to_numpy(), err_msg=f"{qname}.{c}"
            )
