"""Core columnar substrate + kernel unit tests."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ballista_tpu import schema, Int32, Int64, Decimal, Utf8, Date32, Boolean
from ballista_tpu.columnar import ColumnBatch, Dictionary
from ballista_tpu import col, lit, date_lit
from ballista_tpu.expr import ScalarFunction, Like, InList
from ballista_tpu.kernels.expr_eval import Evaluator
from ballista_tpu.kernels.aggregate import (
    AggInput,
    grouped_aggregate,
    scalar_aggregate,
)
from ballista_tpu.kernels.sort import sort_permutation
from ballista_tpu.kernels import join as join_k


def build_batch():
    import datetime as dt

    s = schema(
        ("a", Int64),
        ("b", Decimal(2)),
        ("flag", Utf8),
        ("d", Date32),
    )
    epoch = dt.date(1970, 1, 1)
    days = [
        (dt.date.fromisoformat(x) - epoch).days
        for x in ["1994-01-01", "1994-06-01", "1995-01-01", "1995-06-01", "1996-01-01"]
    ]
    batch = ColumnBatch.from_pydict(
        s,
        {
            "a": [1, 2, 3, 4, 5],
            "b": [1.25, 2.50, 3.75, 5.00, 6.25],
            "flag": ["A", "B", "A", "C", "B"],
            "d": days,
        },
        capacity=8,
    )
    return s, batch


def test_batch_roundtrip():
    s, b = build_batch()
    assert b.capacity == 8
    assert b.num_rows_host() == 5
    d = b.to_pydict()
    assert list(d["a"]) == [1, 2, 3, 4, 5]
    assert list(d["flag"]) == ["A", "B", "A", "C", "B"]
    np.testing.assert_allclose(d["b"], [1.25, 2.5, 3.75, 5.0, 6.25])


def test_batch_is_pytree():
    s, b = build_batch()
    leaves, treedef = jax.tree_util.tree_flatten(b)
    b2 = jax.tree_util.tree_unflatten(treedef, leaves)
    assert b2.schema == s
    # jit a function over the batch
    @jax.jit
    def f(batch):
        return batch.column("a").values.sum()

    assert int(f(b)) == 15  # padding zeros don't affect the raw sum here


def test_expr_arithmetic_and_compare():
    s, b = build_batch()
    ev = Evaluator(s)
    # decimal multiply: b * b -> scale 4
    r = ev.evaluate(col("b") * col("b"), b)
    assert r.dtype.kind == "decimal" and r.dtype.scale == 4
    vals = np.asarray(r.values)[:5]
    np.testing.assert_array_equal(vals, [15625, 62500, 140625, 250000, 390625])

    # predicate with date + string compare + decimal literal
    pred = (col("d") < date_lit("1995-01-01")) & (col("b") >= lit(2.0))
    mask = np.asarray(ev.evaluate_predicate(pred, b))
    assert list(mask[:5]) == [False, True, False, False, False]


def test_expr_utf8_ops():
    s, b = build_batch()
    ev = Evaluator(s)
    m = np.asarray(ev.evaluate_predicate(col("flag") == lit("A"), b))[:5]
    assert list(m) == [True, False, True, False, False]
    m = np.asarray(ev.evaluate_predicate(col("flag") >= lit("B"), b))[:5]
    assert list(m) == [False, True, False, True, True]
    m = np.asarray(ev.evaluate_predicate(InList(col("flag"), [lit("A"), lit("C")]), b))[:5]
    assert list(m) == [True, False, True, True, False]
    m = np.asarray(ev.evaluate_predicate(Like(col("flag"), "%A%"), b))[:5]
    assert list(m) == [True, False, True, False, False]


def test_date_extract():
    s, b = build_batch()
    ev = Evaluator(s)
    r = ev.evaluate(ScalarFunction("extract_year", [col("d")]), b)
    assert list(np.asarray(r.values)[:5]) == [1994, 1994, 1995, 1995, 1996]
    r = ev.evaluate(ScalarFunction("extract_month", [col("d")]), b)
    assert list(np.asarray(r.values)[:5]) == [1, 6, 1, 6, 1]


def test_grouped_aggregate():
    # group 8 rows (6 live) by small key; sums exact in int64
    keys = jnp.asarray([2, 1, 2, 1, 3, 2, 0, 0], dtype=jnp.int64)
    live = jnp.asarray([1, 1, 1, 1, 1, 1, 0, 0], dtype=bool)
    vals = jnp.asarray([10, 20, 30, 40, 50, 60, 70, 80], dtype=jnp.int64)
    res = grouped_aggregate(
        [keys], live,
        [AggInput("sum", vals, None), AggInput("count", None, None),
         AggInput("min", vals, None), AggInput("max", vals, None)],
        group_capacity=4,
    )
    assert int(res.num_groups) == 3
    gv = np.asarray(res.group_valid)
    assert list(gv) == [True, True, True, False]
    # groups sorted by key: 1, 2, 3
    np.testing.assert_array_equal(np.asarray(res.aggregates[0])[:3], [60, 100, 50])
    np.testing.assert_array_equal(np.asarray(res.aggregates[1])[:3], [2, 3, 1])
    np.testing.assert_array_equal(np.asarray(res.aggregates[2])[:3], [20, 10, 50])
    np.testing.assert_array_equal(np.asarray(res.aggregates[3])[:3], [40, 60, 50])
    # rep rows point at first occurrence per key group
    rep = np.asarray(res.rep_indices)[:3]
    np.testing.assert_array_equal(np.asarray(keys)[rep], [1, 2, 3])


def test_multikey_null_groups():
    # NULL keys form their own group; all-NULL aggregates go NULL
    k1 = jnp.asarray([1, 1, 2, 2, 1], dtype=jnp.int64)
    kv = jnp.asarray([True, True, False, False, True])
    live = jnp.ones(5, dtype=bool)
    vals = jnp.asarray([10, 20, 30, 40, 50], dtype=jnp.int64)
    vv = jnp.asarray([True, True, False, False, True])
    res = grouped_aggregate(
        [k1], live,
        [AggInput("sum", vals, vv), AggInput("min", vals, vv),
         AggInput("count", None, vv)],
        group_capacity=4, key_validities=[kv],
    )
    assert int(res.num_groups) == 2
    sums = np.asarray(res.aggregates[0])[:2]
    counts = np.asarray(res.aggregates[2])[:2]
    avalid = np.asarray(res.agg_valid[0])[:2]
    # NULL-key group sorts first (validity 0 < 1): all inputs NULL there
    assert list(counts) == [0, 3]
    assert list(avalid) == [False, True]
    assert sums[1] == 80


def test_scalar_aggregate():
    live = jnp.asarray([True, True, False, True])
    vals = jnp.asarray([5, 7, 100, 3], dtype=jnp.int64)
    out, valids = scalar_aggregate(
        live,
        [AggInput("sum", vals, None), AggInput("count", None, None),
         AggInput("min", vals, None), AggInput("max", vals, None)],
    )
    assert [int(x) for x in out] == [15, 3, 3, 7]
    assert all(bool(v) for v in valids)


def test_avg_fixed_overflow_safe():
    from ballista_tpu.kernels.aggregate import avg_fixed

    s = jnp.asarray(8 * 1_700_000_000_000, dtype=jnp.int64)
    c = jnp.asarray(8, dtype=jnp.int64)
    assert int(avg_fixed(s, c, 0)) == 1_700_000_000_000 * 10**6
    # decimal(2) input
    assert int(avg_fixed(jnp.int64(707), jnp.int64(2), 2)) == 3_535_000


def test_sort_permutation_multikey():
    k1 = jnp.asarray([1, 0, 1, 0, 2], dtype=jnp.int64)
    k2 = jnp.asarray([5, 9, 3, 7, 1], dtype=jnp.int64)
    live = jnp.asarray([True, True, True, True, False])
    perm = np.asarray(sort_permutation([(k1, True), (k2, False)], live))
    # live rows: k1 asc, k2 desc -> (0,9)=1, (0,7)=3, (1,5)=0, (1,3)=2; dead 4 last
    np.testing.assert_array_equal(perm, [1, 3, 0, 2, 4])


def test_join_unique_probe():
    bk = jnp.asarray([10, 20, 30, 0], dtype=jnp.int64)
    bl = jnp.asarray([True, True, True, False])
    table = join_k.build_lookup(bk, bl)
    pk = jnp.asarray([20, 99, 10, 30, 20], dtype=jnp.int64)
    pl = jnp.asarray([True, True, True, False, True])
    rows, matched = join_k.probe_unique(table, pk, pl)
    m = np.asarray(matched)
    np.testing.assert_array_equal(m, [True, False, True, False, True])
    r = np.asarray(rows)
    assert np.asarray(bk)[r[0]] == 20
    assert np.asarray(bk)[r[2]] == 10


def test_join_expand():
    bk = jnp.asarray([1, 1, 2, 5], dtype=jnp.int64)
    bl = jnp.ones(4, dtype=bool)
    table = join_k.build_lookup(bk, bl)
    pk = jnp.asarray([1, 2, 3], dtype=jnp.int64)
    pl = jnp.ones(3, dtype=bool)
    prow, brow, olive, total = join_k.probe_expand(table, pk, pl, out_capacity=8)
    assert int(total) == 3
    ol = np.asarray(olive)
    assert ol.sum() == 3
    got = sorted(
        (int(np.asarray(pk)[p]), int(np.asarray(bk)[b]))
        for p, b, l in zip(np.asarray(prow), np.asarray(brow), ol) if l
    )
    assert got == [(1, 1), (1, 1), (2, 2)]


def test_narrow_wire_upload_exact():
    """Narrow-on-wire transfer must be value-exact incl. negatives and
    int8/int16/int32 boundary values, and must widen back to the
    logical device dtype."""
    import os

    import jax.numpy as jnp

    from ballista_tpu import columnar as col_mod
    from ballista_tpu.columnar import ColumnBatch
    from ballista_tpu.datatypes import Int64, Schema, Field

    old = col_mod._NARROW_WIRE
    col_mod._NARROW_WIRE = True
    try:
        sch = Schema([Field("a", Int64), Field("b", Int64), Field("c", Int64)])
        data = {
            "a": np.array([-128, 127, 0], np.int64),          # int8 fits
            "b": np.array([-32768, 32767, 5], np.int64),      # int16 fits
            "c": np.array([2**40, -2**40, 1], np.int64),      # no narrowing
        }
        b = ColumnBatch.from_numpy(sch, data)
        for name in data:
            c = b.column(name)
            assert c.values.dtype == jnp.int64
            np.testing.assert_array_equal(
                np.asarray(c.values)[:3], data[name])
    finally:
        col_mod._NARROW_WIRE = old


def test_join_dense_probe_exact():
    """Dense direct-index probe must match the sorted probe bit-for-bit,
    including negatives, range boundaries, and out-of-range probe keys."""
    import jax.numpy as jnp

    from ballista_tpu.kernels import join as join_k

    bk = jnp.asarray(np.array([-5, -2, 0, 7, 12], np.int64))
    bl = jnp.asarray(np.array([True, True, False, True, True]))
    rows, dup = join_k.build_dense(bk, bl, jnp.int64(-5), 18)
    assert not bool(dup)
    table = join_k.BuildTable(
        sorted_keys=None, order=None, num_live=jnp.int32(4),
        dense_rows=rows, dense_base=jnp.int64(-5))
    sorted_table = join_k.build_lookup(bk, bl)
    pk = jnp.asarray(np.array([-5, -2, 0, 7, 12, -6, 13, 999, -999], np.int64))
    pl = jnp.ones(9, bool)
    r_dense, m_dense = join_k.probe_unique(table, pk, pl)
    r_sorted, m_sorted = join_k.probe_unique(sorted_table, pk, pl)
    np.testing.assert_array_equal(np.asarray(m_dense), np.asarray(m_sorted))
    # matched rows must point at the same build rows
    md = np.asarray(m_dense)
    np.testing.assert_array_equal(np.asarray(r_dense)[md],
                                  np.asarray(r_sorted)[md])
    # dead build row (key 0) must not match
    assert not np.asarray(m_dense)[2]


def test_join_dense_detects_duplicates():
    import jax.numpy as jnp

    from ballista_tpu.kernels import join as join_k

    bk = jnp.asarray(np.array([3, 3, 5], np.int64))
    bl = jnp.ones(3, bool)
    _, dup = join_k.build_dense(bk, bl, jnp.int64(3), 3)
    assert bool(dup)
    # duplicates hidden by the live mask don't count
    bl2 = jnp.asarray(np.array([True, False, True]))
    _, dup2 = join_k.build_dense(bk, bl2, jnp.int64(3), 3)
    assert not bool(dup2)
