"""Pallas fused dense-group accumulation, validated in interpret mode
(no TPU in CI; BALLISTA_PALLAS=interpret routes the dense aggregate path
through the kernel so the whole q1 pipeline exercises it)."""

import os

import numpy as np
import jax.numpy as jnp
import pytest

from ballista_tpu.kernels.pallas_agg import dense_grouped_sums


def test_kernel_exact_signed_large_values():
    rng = np.random.default_rng(1)
    n, G = 4096 + 77, 6  # non-multiple of BLOCK exercises padding
    gids = rng.integers(0, G, n).astype(np.int32)
    live = rng.random(n) < 0.7
    v1 = rng.integers(-(1 << 45), 1 << 45, n)
    v2 = rng.integers(0, 10**7, n)
    sums, counts = dense_grouped_sums(
        jnp.asarray(gids), jnp.asarray(live),
        [jnp.asarray(v1), jnp.asarray(v2)], G, interpret=True,
    )
    for g in range(G):
        m = live & (gids == g)
        assert int(sums[0][g]) == int(v1[m].sum())
        assert int(sums[1][g]) == int(v2[m].sum())
        assert int(counts[g]) == int(m.sum())


def test_empty_group_and_all_dead():
    gids = jnp.asarray(np.array([0, 0, 2], np.int32))
    live = jnp.asarray(np.array([True, False, True]))
    sums, counts = dense_grouped_sums(
        gids, live, [jnp.asarray(np.array([5, 7, 11], np.int64))], 4,
        interpret=True,
    )
    assert [int(x) for x in sums[0]] == [5, 0, 11, 0]
    assert [int(x) for x in counts] == [1, 0, 1, 0]


def test_kernel_exact_g256_with_validity_masks():
    """G=256 (the dense-path ceiling) with validity-masked sums and
    counts: the one-hot matmul formulation must stay exact — the round-2
    kernel statically unrolled per group and rejected masked inputs."""
    from ballista_tpu.kernels.aggregate import (
        AggInput, dense_grouped_aggregate,
    )

    rng = np.random.default_rng(9)
    n, G = 2048 + 33, 256
    gids = rng.integers(0, G, n).astype(np.int32)
    live = rng.random(n) < 0.8
    v1 = rng.integers(-(1 << 49), 1 << 49, n)  # 4x13-bit limb headroom
    v2 = rng.integers(0, 10**9, n)
    valid1 = rng.random(n) < 0.6
    import os

    os.environ["BALLISTA_PALLAS"] = "interpret"
    try:
        res = dense_grouped_aggregate(
            jnp.asarray(gids), jnp.asarray(live),
            [
                AggInput("sum", jnp.asarray(v1), jnp.asarray(valid1)),
                AggInput("sum", jnp.asarray(v2), None),
                AggInput("count", None, jnp.asarray(valid1)),
                AggInput("count", None, None),
                # min stays on the XLA dense path, split per aggregate
                AggInput("min", jnp.asarray(v2), None),
            ],
            G,
        )
    finally:
        del os.environ["BALLISTA_PALLAS"]
    for g in range(0, G, 17):
        m = live & (gids == g)
        mv = m & valid1
        assert int(res.aggregates[0][g]) == int(v1[mv].sum())
        assert bool(res.agg_valid[0][g]) == bool(mv.any())
        assert int(res.aggregates[1][g]) == int(v2[m].sum())
        assert int(res.aggregates[2][g]) == int(mv.sum())
        assert int(res.aggregates[3][g]) == int(m.sum())
        if m.any():
            assert int(res.aggregates[4][g]) == int(v2[m].min())


def test_auto_gate_small_cpu_batches_use_interpret(monkeypatch):
    """With no env set, small CPU batches route through the kernel in
    interpret mode automatically (the gate flips to the compiled kernel
    on real TPU hardware)."""
    monkeypatch.delenv("BALLISTA_PALLAS", raising=False)
    from ballista_tpu.kernels import aggregate as agg_mod
    from ballista_tpu.kernels.aggregate import (
        AggInput, dense_grouped_aggregate,
    )

    calls = {}
    orig = agg_mod._dense_grouped_pallas

    def spy(gids, live, aggs, num_groups, interpret):
        calls["interpret"] = interpret
        return orig(gids, live, aggs, num_groups, interpret)

    monkeypatch.setattr(agg_mod, "_dense_grouped_pallas", spy)
    gids = jnp.asarray(np.array([0, 1, 1, 2], np.int32))
    live = jnp.ones(4, bool)
    res = dense_grouped_aggregate(
        gids, live, [AggInput("sum", jnp.arange(4, dtype=jnp.int64), None)],
        4,
    )
    assert calls.get("interpret") is True
    assert [int(x) for x in res.aggregates[0][:3]] == [0, 3, 3]


def test_q1_through_pallas_interpret(tmp_path, monkeypatch):
    """TPC-H q1 with the dense path routed through the Pallas kernel
    matches the oracle end to end."""
    monkeypatch.setenv("BALLISTA_PALLAS", "interpret")
    from benchmarks.tpch import datagen, oracle
    from benchmarks.tpch.schema_def import register_tpch
    from ballista_tpu.client import BallistaContext

    d = str(tmp_path / "data")
    datagen.generate(d, scale=0.002, num_parts=1)
    ctx = BallistaContext.standalone()
    register_tpch(ctx, d, "tbl")
    sql = open(os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                            "tpch", "queries", "q1.sql")).read()
    got = ctx.sql(sql).collect().reset_index(drop=True)
    exp = oracle.ORACLES["q1"](oracle.load_tables(d)).reset_index(drop=True)
    assert len(got) == len(exp)
    for c in exp.columns:
        g, e = got[c], exp[c]
        if e.dtype.kind in "fc":
            np.testing.assert_allclose(g.astype(float), e.astype(float),
                                       rtol=1e-6, atol=1e-6, err_msg=c)
        else:
            np.testing.assert_array_equal(g.to_numpy(), e.to_numpy(),
                                          err_msg=c)
