"""Per-query latency ledger tests (PR 20 tentpole + satellites).

Covers: the standalone path's fixed-schema ledger whose phases sum to
the measured wall time; assembly primitives (normalization, per-task
delta extraction, the cross-executor merge, the scheduler's job-terminal
assembly); ``ledger.*`` deltas riding TaskProfile.phases through the
proto round-trip unchanged; the process LedgerLog behind
``system.latency``; SLO histograms + the exemplar store behind
``system.exemplars`` (full-ledger JSON round-trip, most-recent-wins,
+Inf bucket); a LocalCluster e2e (scheduler-assembled ledgers queryable
over SQL, ``ctx.last_query_ledger()`` fetching the client-merged view);
the ring right-walk micro-test (extraction cost bounded by WINDOW size,
not ring size); the slow-query artifact cap (flood stays bounded, knob
registered in ``system.settings``); and the drift-cancelling < 5%
warm-q1 overhead gate flipping ``BALLISTA_LEDGER``.
"""

import json
import os
import time

import pytest

from ballista_tpu.client import BallistaContext
from ballista_tpu.datatypes import Float64, Int64, Utf8, schema
from ballista_tpu.observability import ledger as obs_ledger
from ballista_tpu.observability import metrics as obs_metrics
from ballista_tpu.observability import registry as obs_registry
from ballista_tpu.observability import tracing as obs_tracing
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu import serde


@pytest.fixture
def ctx():
    c = BallistaContext.standalone()
    c.register_memtable(
        "t", schema(("k", Utf8), ("a", Int64), ("b", Float64)),
        {"k": ["x", "y", "z"] * 20,
         "a": list(range(60)),
         "b": [float(i) / 4 for i in range(60)]},
    )
    return c


@pytest.fixture
def ledger_env():
    """Restore ledger enablement + log capacity however a test mangles
    them, and leave the process log/exemplar store fresh on both sides."""
    saved = {k: os.environ.get(k)
             for k in ("BALLISTA_LEDGER", "BALLISTA_LEDGER_LOG")}
    obs_ledger.reset_process_log()
    obs_metrics.reset_latency_exemplars()
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    obs_ledger.reconfigure()
    obs_ledger.reset_process_log()
    obs_metrics.reset_latency_exemplars()


def _ledger_total(led):
    return sum(led["phases"].values()) + led["unattributed_seconds"]


# ---------------------------------------------------------------------------
# standalone path: schema + phases sum to wall
# ---------------------------------------------------------------------------


def test_standalone_ledger_schema_and_sum(ctx, ledger_env):
    out = ctx.sql("SELECT k, sum(a) AS s FROM t GROUP BY k").collect()
    assert len(out) == 3
    led = ctx.last_query_ledger()
    assert led is not None
    assert led["origin"] == "standalone"
    assert led["status"] == "completed"
    assert set(led["phases"]) == set(obs_ledger.LEDGER_PHASES)
    assert led["wall_seconds"] > 0.0
    # the standalone recorder attributes the unexplained remainder to
    # device_execute, so phases + unattributed reconstruct the wall
    # exactly (up to per-phase rounding)
    assert abs(_ledger_total(led) - led["wall_seconds"]) < 1e-4, led
    assert led["phases"]["planning"] >= 0.0
    assert led["phases"]["host_decode"] > 0.0  # DataFrame materialization
    # cluster-only phases stay present but zero
    assert led["phases"]["queue_wait"] == 0.0
    assert led["phases"]["shuffle_fetch"] == 0.0
    # the same ledger landed in the process log (system.latency source)
    last = obs_ledger.process_ledger_log().last()
    assert last is not None and last["job_id"] == led["job_id"]


def test_standalone_ledger_rows_via_sql(ctx, ledger_env):
    ctx.sql("SELECT sum(a) AS s FROM t").collect()
    led = ctx.last_query_ledger()
    ctx._plan_cache.clear()
    rows = ctx.sql(
        "SELECT job_id, phase, seconds, fraction, wall_seconds "
        "FROM system.latency").collect()
    mine = rows[rows["job_id"] == led["job_id"]]
    # one row per phase plus the explicit unattributed remainder
    assert set(mine["phase"]) == \
        set(obs_ledger.LEDGER_PHASES) | {"unattributed"}
    assert (mine["seconds"] >= 0.0).all()
    assert (mine["fraction"] <= 1.0 + 1e-9).all()


def test_ledger_disabled_records_nothing(ctx, ledger_env):
    os.environ["BALLISTA_LEDGER"] = "0"
    obs_ledger.reconfigure()
    before = len(obs_ledger.process_ledger_log().entries())
    ctx.sql("SELECT sum(a) AS s FROM t").collect()
    assert ctx.last_query_ledger() is None
    assert len(obs_ledger.process_ledger_log().entries()) == before


# ---------------------------------------------------------------------------
# assembly primitives
# ---------------------------------------------------------------------------


def test_build_ledger_normalizes_to_fixed_schema():
    led = obs_ledger.build_ledger(
        "job-1", 2.0, "cluster", "completed",
        phases={"compile": 0.5, "device_execute": 1.0,
                "bogus_phase": 9.0, "queue_wait": -0.25,
                "planning": "not-a-number"})
    assert set(led["phases"]) == set(obs_ledger.LEDGER_PHASES)
    assert "bogus_phase" not in led["phases"]
    assert led["phases"]["queue_wait"] == 0.0  # negatives clamp
    assert led["phases"]["planning"] == 0.0    # junk drops
    assert led["unattributed_seconds"] == pytest.approx(0.5)
    assert _ledger_total(led) == pytest.approx(led["wall_seconds"])


def test_task_ledger_phases_extracts_spans_and_remainder():
    records = [
        {"name": "shuffle.fetch", "dur": 0.10},
        {"name": "shuffle.fetch", "dur": 0.05},
        {"name": "dataplane.write", "dur": 0.20},
        {"name": "cache.lookup", "dur": 0.01},
        {"name": "executor.task", "dur": 1.0},  # envelope: not a phase
    ]
    deltas = obs_ledger.task_ledger_phases(records, 1.0,
                                           compile_seconds=0.25)
    assert deltas["ledger.shuffle_fetch"] == pytest.approx(0.15)
    assert deltas["ledger.shuffle_write"] == pytest.approx(0.20)
    assert deltas["ledger.cache_lookup"] == pytest.approx(0.01)
    assert deltas["ledger.compile"] == pytest.approx(0.25)
    # device_execute is the task's unattributed remainder
    assert deltas["ledger.device_execute"] == pytest.approx(0.39)
    assert all(k.startswith("ledger.") for k in deltas)


def test_merge_task_phases_sums_across_executors():
    # two executors' worth of per-task payloads: summing IS the merge
    # (phases are disjoint slices of task wall time); non-ledger phase
    # keys (ingest counters etc.) must be ignored
    payloads = [
        {"phases": {"ledger.shuffle_fetch": 0.1,
                    "ledger.device_execute": 0.4, "parse": 9.0}},
        {"phases": {"ledger.shuffle_fetch": 0.3,
                    "ledger.compile": 0.2, "ledger.junk_val": "x"}},
        {"phases": None},
    ]
    merged = obs_ledger.merge_task_phases(payloads)
    assert merged == {"shuffle_fetch": pytest.approx(0.4),
                      "device_execute": pytest.approx(0.4),
                      "compile": pytest.approx(0.2)}
    led = obs_ledger.assemble_job_ledger(
        "job-2", 2.0, "completed",
        stamps={"queue_wait": 0.5, "planning": 0.1},
        task_payloads=payloads)
    assert led["phases"]["queue_wait"] == pytest.approx(0.5)
    assert led["phases"]["shuffle_fetch"] == pytest.approx(0.4)
    assert led["unattributed_seconds"] == pytest.approx(0.4)


def test_ledger_deltas_survive_task_profile_proto():
    # the deltas ride TaskProfile.phases as ledger.* keys — no proto
    # change — and must come back float-typed and byte-identical
    phases = {"parse": 0.5,
              "ledger.shuffle_fetch": 0.123456,
              "ledger.device_execute": 1.5}
    profile = {"t0": 10.0, "wall_seconds": 2.0, "pid": 42,
               "role": "executor", "executor_id": "exec-1",
               "records": [], "phases": phases, "compile": {},
               "memory": {}}
    msg = pb.TaskProfile()
    serde.task_profile_to_proto(profile, msg)
    back = serde.task_profile_from_proto(msg)
    assert back["phases"] == phases
    merged = obs_ledger.merge_task_phases([back])
    assert merged == {"shuffle_fetch": pytest.approx(0.123456),
                      "device_execute": pytest.approx(1.5)}


# ---------------------------------------------------------------------------
# the process log (system.latency source)
# ---------------------------------------------------------------------------


def test_ledger_log_capacity_and_rows(ledger_env):
    log = obs_ledger.LedgerLog(capacity=2)
    for i in range(3):
        log.record(obs_ledger.build_ledger(
            f"job-{i}", 1.0, "standalone", "completed",
            phases={"device_execute": 0.5}))
    entries = log.entries()
    assert [e["job_id"] for e in entries] == ["job-1", "job-2"]
    rows = log.rows()
    # one row per retained query per phase + the unattributed row
    assert len(rows) == 2 * (len(obs_ledger.LEDGER_PHASES) + 1)
    unattr = [r for r in rows if r["phase"] == "unattributed"]
    assert all(r["seconds"] == pytest.approx(0.5) for r in unattr)
    assert all(r["fraction"] == pytest.approx(0.5) for r in unattr)


def test_ledger_log_since_filter(ledger_env):
    log = obs_ledger.LedgerLog(capacity=8)
    log.record(obs_ledger.build_ledger("old", 1.0, "x", "completed"))
    cut = time.time()
    log.record(obs_ledger.build_ledger("new", 1.0, "x", "completed"))
    assert [e["job_id"] for e in log.entries(since=cut)] == ["new"]


# ---------------------------------------------------------------------------
# SLO histograms + exemplar store (system.exemplars source)
# ---------------------------------------------------------------------------


def test_exemplar_store_roundtrip_and_most_recent_wins(ledger_env):
    obs_registry.reset_histograms()
    led_a = obs_ledger.build_ledger("job-a", 0.3, "cluster", "completed",
                                    phases={"compile": 0.2})
    led_b = obs_ledger.build_ledger("job-b", 0.4, "cluster", "completed",
                                    phases={"compile": 0.15})
    obs_metrics.observe_query_ledger(led_a)
    obs_metrics.observe_query_ledger(led_b)  # same 0.5s bucket: b wins
    rows = obs_metrics.exemplar_rows()
    wall = [r for r in rows
            if r["family"] == obs_metrics.SLO_LATENCY_FAMILY
            and r["bucket_le"] == 0.5]
    assert len(wall) == 1 and wall[0]["job_id"] == "job-b"
    # ledger_json carries the exemplar query's FULL ledger
    back = json.loads(wall[0]["ledger_json"])
    assert back == led_b
    # every phase family cell retained an exemplar too
    phase_rows = [r for r in rows
                  if r["family"] == obs_metrics.SLO_PHASE_FAMILY]
    assert {r["phase"] for r in phase_rows} == \
        set(obs_ledger.LEDGER_PHASES)
    # and the histograms counted both queries in every cell
    snap = obs_registry.histogram_snapshot()
    cells = snap[obs_metrics.SLO_LATENCY_FAMILY]
    assert len(cells) == 1 and cells[0][3] == 2  # count == 2 queries


def test_exemplar_inf_bucket(ledger_env):
    obs_registry.reset_histograms()
    led = obs_ledger.build_ledger("job-slow", 500.0, "cluster",
                                  "completed",
                                  phases={"device_execute": 500.0})
    obs_metrics.observe_query_ledger(led)
    rows = [r for r in obs_metrics.exemplar_rows()
            if r["family"] == obs_metrics.SLO_LATENCY_FAMILY]
    assert rows and rows[-1]["bucket_le"] == float("inf")
    assert rows[-1]["job_id"] == "job-slow"
    # the +Inf sentinel survives the system-table float column
    assert json.loads(rows[-1]["ledger_json"])["wall_seconds"] == 500.0


def test_histogram_merge_across_executor_observations(ledger_env):
    # in a cluster every completed job is observed once, at the
    # scheduler — but multiple schedulers/processes can scrape-merge by
    # bucket addition. Verify bucket counts are additive and cumulative.
    obs_registry.reset_histograms()
    for wall in (0.04, 0.2, 0.2, 3.0):
        obs_metrics.observe_query_ledger(obs_ledger.build_ledger(
            "j", wall, "cluster", "completed"))
    (_, counts, total, n), = \
        obs_registry.histogram_snapshot()[obs_metrics.SLO_LATENCY_FAMILY]
    buckets = obs_registry.HISTOGRAM_BUCKETS
    assert n == 4 and total == pytest.approx(3.44)
    assert counts[buckets.index(0.05)] == 1   # cumulative: <= 0.05
    assert counts[buckets.index(0.25)] == 3   # 0.04 + two 0.2s
    assert counts[buckets.index(5.0)] == 4    # everything
    assert counts == sorted(counts)           # cumulative monotone


# ---------------------------------------------------------------------------
# satellite: ring right-walk — extraction cost bounded by window size
# ---------------------------------------------------------------------------


class _CountingRecord(dict):
    """Ring record that counts field reads: ring_records(since=...) must
    examine O(window) records, not O(ring)."""
    reads = [0]

    def get(self, k, default=None):
        if k in ("ts", "dur"):
            type(self).reads[0] += 1
        return dict.get(self, k, default)


def test_ring_records_since_walks_only_the_window():
    saved = os.environ.get("BALLISTA_FLIGHT_RECORDER")
    os.environ.pop("BALLISTA_FLIGHT_RECORDER", None)
    obs_tracing.reconfigure()
    try:
        assert obs_tracing.flight_recorder_enabled()  # default on
        ring = obs_tracing._ring()
        snap_before = list(ring)
        ring.clear()
        n_old, n_window = 3000, 16
        for i in range(n_old):
            ring.append(_CountingRecord(name="old", ts=100.0 + i * 1e-3,
                                        dur=0.0))
        since = 1000.0
        for i in range(n_window):
            ring.append(_CountingRecord(name="new", ts=since + i,
                                        dur=0.0))
        _CountingRecord.reads[0] = 0
        out = obs_tracing.ring_records(since=since)
        assert len(out) == n_window
        assert all(r["name"] == "new" for r in out)
        # right-walk: window records + the ONE old record that stops the
        # walk are examined (2 field reads each) — nothing near n_old
        assert _CountingRecord.reads[0] <= 2 * (n_window + 1), \
            _CountingRecord.reads[0]
    finally:
        ring = obs_tracing._ring()
        if ring is not None:
            ring.clear()
            ring.extend(snap_before)
        if saved is not None:
            os.environ["BALLISTA_FLIGHT_RECORDER"] = saved
        obs_tracing.reconfigure()


# ---------------------------------------------------------------------------
# satellite: slow-query artifact flood stays bounded
# ---------------------------------------------------------------------------


def test_slow_artifact_flood_capped(tmp_path, monkeypatch):
    from ballista_tpu.observability import distributed as obs_dist

    d = tmp_path / "slow"
    d.mkdir()
    monkeypatch.setenv("BALLISTA_SLOW_QUERY_DIR", str(d))
    monkeypatch.setenv("BALLISTA_SLOW_QUERY_MAX_ARTIFACTS", "5")
    for i in range(12):
        p = d / f"ballista-profile-{i:03d}.json"
        p.write_text("{}")
        os.utime(p, (1000 + i, 1000 + i))
    # a bystander file in the shared dir must never be touched
    (d / "keep.txt").write_text("x")
    removed = obs_dist.prune_slow_query_artifacts()
    assert removed == 7
    kept = sorted(n for n in os.listdir(d)
                  if n.startswith("ballista-profile-"))
    # the NEWEST survive — the dumps an operator is about to look at
    assert kept == [f"ballista-profile-{i:03d}.json"
                    for i in range(7, 12)]
    assert (d / "keep.txt").exists()
    # repeated floods stay bounded (the cap is enforced per dump)
    for i in range(12, 20):
        (d / f"ballista-profile-{i:03d}.json").write_text("{}")
        obs_dist.prune_slow_query_artifacts()
        n = len([x for x in os.listdir(d)
                 if x.startswith("ballista-profile-")])
        assert n <= 5
    # 0 disables pruning entirely
    monkeypatch.setenv("BALLISTA_SLOW_QUERY_MAX_ARTIFACTS", "0")
    (d / "ballista-profile-999.json").write_text("{}")
    assert obs_dist.prune_slow_query_artifacts() == 0


def test_slow_artifact_cap_knob_registered(ctx):
    rows = ctx.sql(
        "SELECT name, value FROM system.settings").collect()
    names = set(rows["name"])
    assert {"BALLISTA_SLOW_QUERY_MAX_ARTIFACTS", "BALLISTA_LEDGER",
            "BALLISTA_LEDGER_LOG"} <= names, names


# ---------------------------------------------------------------------------
# cluster path: scheduler-assembled ledgers, SQL + client fetch
# ---------------------------------------------------------------------------


def test_cluster_ledger_end_to_end(tmp_path, ledger_env):
    from ballista_tpu.distributed.executor import LocalCluster

    csv = tmp_path / "t.csv"
    with open(csv, "w") as f:
        f.write("k,a\n")
        for i in range(40):
            f.write(f"{'xy'[i % 2]},{i}\n")

    cluster = LocalCluster(num_executors=2, metrics_port=0)
    try:
        ctx = BallistaContext.remote("localhost", cluster.port)
        ctx.register_csv("t", str(csv), schema(("k", Utf8), ("a", Int64)))
        out = ctx.sql(
            "SELECT k, sum(a) AS s FROM t GROUP BY k ORDER BY k"
        ).collect()
        assert list(out["s"]) == [380, 400]
        job_id = ctx._last_job_id
        assert job_id

        # the client-merged view: scheduler phases + client envelope
        led = ctx.last_query_ledger()
        assert led is not None, "remote ledger fetch came back empty"
        assert led["job_id"] == job_id and led["origin"] == "client"
        assert led["status"] == "completed"
        assert set(led["phases"]) == set(obs_ledger.LEDGER_PHASES)
        assert led["wall_seconds"] > 0.0
        # the executor side attributed real work (on a tiny query the
        # device_execute remainder can clamp to 0 when the process-wide
        # compile delta dominates each task's wall, so assert on the
        # executor-derived mass, not one phase)...
        exec_mass = (led["phases"]["device_execute"]
                     + led["phases"]["compile"]
                     + led["phases"]["shuffle_write"]
                     + led["phases"]["shuffle_fetch"])
        assert exec_mass > 0.0, led
        # ...the multi-stage plan wrote shuffle partitions...
        assert led["phases"]["shuffle_write"] > 0.0
        # ...and the client stamped its envelope
        assert led["phases"]["host_decode"] > 0.0
        assert led["unattributed_seconds"] >= 0.0

        # scheduler's LedgerLog serves system.latency over plain SQL
        ctx._plan_cache.clear()
        rows = ctx.sql(
            "SELECT job_id, origin, status, phase, seconds "
            "FROM system.latency").collect()
        mine = rows[rows["job_id"] == job_id]
        assert set(mine["phase"]) == \
            set(obs_ledger.LEDGER_PHASES) | {"unattributed"}
        assert set(mine["origin"]) == {"cluster"}
        assert set(mine["status"]) == {"completed"}

        # and every job fed the exemplar store with its full ledger
        ctx._plan_cache.clear()
        ex_rows = ctx.sql(
            "SELECT family, phase, bucket_le, job_id, ledger_json "
            "FROM system.exemplars").collect()
        assert len(ex_rows) > 0
        full = json.loads(ex_rows.iloc[0]["ledger_json"])
        assert set(full["phases"]) == set(obs_ledger.LEDGER_PHASES)
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# overhead gate: warm q1, ledger on vs off, < 5%
# ---------------------------------------------------------------------------


def test_ledger_overhead_q1_under_5pct(tmp_path_factory, ledger_env):
    from benchmarks.tpch import datagen
    from benchmarks.tpch.schema_def import register_tpch

    data_dir = str(tmp_path_factory.mktemp("tpch_ledger"))
    datagen.generate(data_dir, scale=0.01, num_parts=1)
    ctx = BallistaContext.standalone()
    register_tpch(ctx, data_dir, "tbl")
    qdir = os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                        "tpch", "queries")
    df = ctx.sql(open(os.path.join(qdir, "q1.sql")).read())
    df.collect()  # warm: jit compile + table caches

    def sample(flag):
        os.environ["BALLISTA_LEDGER"] = flag
        obs_ledger.reconfigure()
        t0 = time.perf_counter()
        for _ in range(3):
            df.collect()
        return time.perf_counter() - t0

    sample("1")
    sample("0")

    def measure():
        # interleaved pairs with alternating order so load spikes and
        # monotonic ramps hit both sides equally; medians absorb the
        # rest (same drift-cancelling shape as the metrics gate)
        offs, ons = [], []
        for i in range(9):
            if i % 2 == 0:
                offs.append(sample("0"))
                ons.append(sample("1"))
            else:
                ons.append(sample("1"))
                offs.append(sample("0"))
        return sorted(offs)[4], sorted(ons)[4]

    for attempt in range(3):
        t_off, t_on = measure()
        if t_on <= t_off * 1.05 + 2e-3:
            return
    overhead = (t_on - t_off) / t_off
    raise AssertionError(
        f"ledger overhead {overhead:.1%} (on={t_on:.4f}s off={t_off:.4f}s)"
    )
