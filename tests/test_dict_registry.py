"""Dictionary registry (ISSUE 11): device-resident string encodings.

Pins the tentpole contracts:
- producers intern per (table, column) entries -> partitions/re-scans
  share ONE Dictionary instance and unify degenerates to identity;
- version chains remap through pure integer composition; cross-entry
  pairs build once (cached) and match the legacy searchsorted result;
- Arrow IPC stamps resolve to the SAME in-process instance on read;
- compile/aot.py keys on registry epochs: a dictionary APPEND does not
  invalidate artifacts keyed on older versions, and the per-value
  Python fingerprint loop never runs on the keying path;
- q1/q5/q16 results are byte-identical registry ON vs OFF;
- warm q1 pays < 5% for the plane (drift-cancelling scheme, PR-1);
- the vectorized stable_hashes matches the reference FNV-1a loop;
- dev/check_dict_sites.py keeps host unify paths from regrowing.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ballista_tpu import columnar_registry as reg
from ballista_tpu.client import BallistaContext
from ballista_tpu.columnar import ColumnBatch, Dictionary
from ballista_tpu.datatypes import Int64, Utf8
from ballista_tpu import schema


@pytest.fixture
def registry_env():
    """Force the registry ON for the test and restore after."""
    old = os.environ.pop("BALLISTA_DICT_REGISTRY", None)
    yield
    if old is not None:
        os.environ["BALLISTA_DICT_REGISTRY"] = old


def _fresh_key(tag: str) -> tuple:
    return ("test", tag, time.monotonic_ns())


# ---------------------------------------------------------------------------
# satellite: vectorized stable_hashes
# ---------------------------------------------------------------------------


def _reference_fnv1a(values) -> np.ndarray:
    """The pre-vectorization per-value loop, verbatim (the regression
    anchor: hashes feed shuffle partitioning, so they may NEVER move)."""
    out = np.empty(len(values), dtype=np.int64)
    for i, v in enumerate(values):
        h = 0xCBF29CE484222325
        for b in str(v).encode("utf-8"):
            h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
        out[i] = np.int64(np.uint64(h))
    return out


def test_stable_hashes_match_reference_loop():
    import random
    import string

    random.seed(11)
    pool = string.printable.replace("\x00", "")
    vals = ["", "a", "ASIA", "EUROPE", "x" * 300, "héllo wörld",
            "日本語テスト", "a\x00b", "trailing  ", "  leading"]
    vals += ["".join(random.choices(pool, k=random.randint(0, 90)))
             for _ in range(800)]
    d = Dictionary(vals)
    got = d.stable_hashes()
    np.testing.assert_array_equal(got, _reference_fnv1a(vals))
    # cached: the shuffle-partitioning path calls this per evaluation
    assert d.stable_hashes() is got
    assert Dictionary([]).stable_hashes().shape == (0,)


def test_stable_hashes_trailing_nul_exact():
    # numpy's fixed-width str view drops trailing U+0000; the scalar
    # fallback keeps those rows exact
    vals = ["a", "a\x00", "\x00", "", "b\x00\x00"]
    np.testing.assert_array_equal(
        Dictionary(vals).stable_hashes(), _reference_fnv1a(vals))


def test_values_str_cached_and_positions():
    d = Dictionary(["aa", "bb", "cc"])
    sv = d.values_str()
    assert d.values_str() is sv
    np.testing.assert_array_equal(
        d.positions_of(np.asarray(["bb", "aa", "cc"], dtype=object)),
        [1, 0, 2])
    lo, hi = d.code_range("bb")
    assert (lo, hi) == (1, 2)


# ---------------------------------------------------------------------------
# registry core: intern / version chains / remaps
# ---------------------------------------------------------------------------


def test_intern_shares_one_instance(registry_env):
    key = _fresh_key("share")
    d1 = reg.intern(key, ["b", "a", "c"][0:0] + ["a", "b", "c"])
    d2 = reg.intern(key, ["a", "b", "c"])
    assert d1 is d2
    assert reg.REGISTRY.stamp_of(d1) is not None
    # equal content under a DIFFERENT key still collapses by epoch
    d3 = reg.REGISTRY.adopt(None, ["a", "b", "c"])
    assert d3 is d1


def test_intern_version_chain_and_integer_remap(registry_env):
    key = _fresh_key("chain")
    v0 = reg.intern(key, ["b", "d", "f"])
    v1 = reg.intern(key, ["a", "b", "z"])  # superset union appended
    assert v1 is not v0
    assert list(map(str, v1.values)) == ["a", "b", "d", "f", "z"]
    assert v0._reg_version == 0 and v1._reg_version == 1
    # subset of the current version: no new version minted
    assert reg.intern(key, ["a", "z"]) is v1
    # v0 -> v1: pure integer composition, no misses
    r = reg.remap_between(v0, v1)
    np.testing.assert_array_equal(r, [1, 2, 3])
    # v1 -> v0: inverse, absent values -> -1
    r_inv = reg.remap_between(v1, v0)
    np.testing.assert_array_equal(r_inv, [-1, 0, 1, 2, -1])
    # identical coding -> no remap at all
    assert reg.remap_between(v1, v1) is None


def test_remap_between_cross_entry_matches_legacy(registry_env):
    b = reg.intern(_fresh_key("build"), ["ape", "bee", "cat"])
    p = reg.intern(_fresh_key("probe"), ["bee", "cow", "cat"])
    r = reg.remap_between(p, b)
    np.testing.assert_array_equal(r, [1, -1, 2])
    # cached: second call returns the same table
    assert reg.remap_between(p, b) is r
    # legacy (registry off) computes the same mapping
    os.environ["BALLISTA_DICT_REGISTRY"] = "off"
    try:
        np.testing.assert_array_equal(reg.remap_between(p, b), [1, -1, 2])
    finally:
        os.environ.pop("BALLISTA_DICT_REGISTRY")


def test_nul_tail_values_stay_legacy(registry_env):
    # value sets numpy's str representation cannot carry are refused
    d = reg.intern(_fresh_key("nul"), ["a", "a\x00"])
    assert reg.REGISTRY.stamp_of(d) is None
    assert [str(v) for v in d.values] == ["a", "a\x00"]
    # and unify with such a member routes through the object-array
    # union, preserving the value (review fix: the str-view fast path
    # would silently strip the trailing NUL)
    other = reg.intern(_fresh_key("nul-other"), ["a", "b"])
    target, _remaps = reg.unify([d, other])
    vals = [str(v) for v in target.values]
    assert "a\x00" in vals and "b" in vals, vals


# ---------------------------------------------------------------------------
# tentpole: unify is a no-op for shared dictionaries, integer-only
# across versions of one entry
# ---------------------------------------------------------------------------


def _batch(d: Dictionary, codes, extra=0):
    s = schema(("k", Utf8), ("v", Int64))
    return ColumnBatch.from_numpy(
        s,
        {"k": np.asarray(codes, np.int32),
         "v": np.arange(len(codes)) + extra},
        {"k": d}, capacity=8)


def test_concat_unify_noop_for_shared_registry_dict(registry_env):
    from ballista_tpu.physical.base import concat_batches

    d = reg.intern(_fresh_key("noop"), ["x", "y", "z"])
    b1, b2 = _batch(d, [0, 1]), _batch(d, [2, 0], extra=10)
    out = concat_batches(b1.schema, [b1, b2])
    assert out.column("k").dictionary is d  # no union dictionary built
    got = out.to_pydict()
    assert [str(v) for v in got["k"]] == ["x", "y", "z", "x"]


def test_concat_unify_versions_never_touches_legacy_union(registry_env,
                                                          monkeypatch):
    from ballista_tpu.physical.base import concat_batches

    key = _fresh_key("vers")
    v0 = reg.intern(key, ["x", "y"])
    v1 = reg.intern(key, ["w", "x", "y"])

    def boom(*a, **k):  # the object-array union path must not run
        raise AssertionError("legacy union invoked on the registry path")

    monkeypatch.setattr(reg.DictionaryRegistry, "_legacy_union", boom)
    b1, b2 = _batch(v0, [0, 1]), _batch(v1, [0, 2], extra=10)
    out = concat_batches(b1.schema, [b1, b2])
    assert out.column("k").dictionary is v1
    got = out.to_pydict()
    assert [str(v) for v in got["k"]] == ["x", "y", "w", "y"]


def test_unify_parts_adopts_and_collapses(registry_env):
    # shuffle-read shape: raw value arrays from two producers of one
    # table -> one adopted instance, codes pass through unremapped
    vals = np.asarray(["a", "b", "c"], dtype=object)
    target, codes = reg.unify_parts([
        (np.asarray([0, 2], np.int32), vals),
        (np.asarray([1], np.int32), vals.copy()),
    ])
    assert isinstance(target, Dictionary)
    # equal content collapsed to ONE adopted instance, codes untouched
    assert reg.REGISTRY.adopt(None, vals) is target
    np.testing.assert_array_equal(codes[0], [0, 2])
    np.testing.assert_array_equal(codes[1], [1])
    # differing producers still remap onto a shared union
    target2, codes2 = reg.unify_parts([
        (np.asarray([0], np.int32), np.asarray(["a", "c"], dtype=object)),
        (np.asarray([1], np.int32), np.asarray(["b", "c"], dtype=object)),
    ])
    assert [str(v) for v in target2.values] == ["a", "b", "c"]
    np.testing.assert_array_equal(codes2[0], [0])
    np.testing.assert_array_equal(codes2[1], [2])


def test_ipc_roundtrip_resolves_to_interned_instance(registry_env,
                                                     tmp_path):
    from ballista_tpu.io import ipc

    d = reg.intern(_fresh_key("ipc"), ["pp", "qq", "rr"])
    b = _batch(d, [0, 2, 1])
    path = str(tmp_path / "part.arrow")
    ipc.write_partition(path, [b])
    names, arrays, nulls, dicts, kinds = ipc.read_partition_arrays(path)
    assert dicts["k"] is d  # stamp resolved, values never re-hydrated
    batches = ipc.batches_from_parts(
        b.schema, [(arrays, nulls, dicts)])
    assert batches[0].column("k").dictionary is d


# ---------------------------------------------------------------------------
# tentpole: AOT keys ride registry epochs
# ---------------------------------------------------------------------------


def test_aot_key_stable_under_dict_append(registry_env, monkeypatch):
    from ballista_tpu.compile import aot

    key = _fresh_key("aotkey")
    v0 = reg.intern(key, ["m", "n"])
    b = _batch(v0, [0, 1])

    def no_loop(self):  # the per-value Python loop must be OFF this path
        raise AssertionError("content_fingerprint loop ran on the "
                             "AOT keying path")

    monkeypatch.setattr(Dictionary, "content_fingerprint", no_loop)
    fp_before = aot._args_fingerprint((b,))
    # an APPEND mints a new version; programs keyed on v0 batches keep
    # their artifacts (same fingerprint), the new version keys fresh
    v1 = reg.intern(key, ["m", "n", "o"])
    assert aot._args_fingerprint((b,)) == fp_before
    assert aot._args_fingerprint((_batch(v1, [0, 1]),)) != fp_before


def test_aot_output_proto_resolves_shared_dictionary(registry_env):
    from ballista_tpu.compile import aot

    d = reg.intern(_fresh_key("aotout"), ["u", "v"])
    b = _batch(d, [1, 0])
    proto = aot._encode_out(b)
    mat = aot._materialize_dicts(proto)
    # the loaded artifact's output dictionary IS the interned instance
    assert mat[2][0][2] is d


# ---------------------------------------------------------------------------
# determinism + overhead gates
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tpch_dir(tmp_path_factory):
    from benchmarks.tpch import datagen

    d = str(tmp_path_factory.mktemp("tpch_reg"))
    datagen.generate(d, scale=0.005, num_parts=2)
    return d


def _collect_queries(data_dir, queries):
    from benchmarks.tpch.schema_def import register_tpch

    ctx = BallistaContext.standalone()
    register_tpch(ctx, data_dir, "tbl")
    qdir = os.path.join(REPO, "benchmarks", "tpch", "queries")
    out = {}
    for q in queries:
        df = ctx.sql(open(os.path.join(qdir, f"{q}.sql")).read())
        out[q] = df.collect()
    return out


def test_determinism_registry_on_vs_off(tpch_dir):
    queries = ("q1", "q5", "q16")
    old = os.environ.pop("BALLISTA_DICT_REGISTRY", None)
    try:
        on = _collect_queries(tpch_dir, queries)
        os.environ["BALLISTA_DICT_REGISTRY"] = "off"
        off = _collect_queries(tpch_dir, queries)
    finally:
        if old is not None:
            os.environ["BALLISTA_DICT_REGISTRY"] = old
        else:
            os.environ.pop("BALLISTA_DICT_REGISTRY", None)
    for q in queries:
        assert list(on[q].columns) == list(off[q].columns)
        for col in on[q].columns:
            a = on[q][col].to_numpy()
            b = off[q][col].to_numpy()
            if a.dtype.kind == "O" or b.dtype.kind == "O":
                assert [str(x) for x in a] == [str(x) for x in b], \
                    f"{q}.{col} differs registry on vs off"
            else:
                np.testing.assert_array_equal(
                    a, b, err_msg=f"{q}.{col} differs registry on vs off")


def test_registry_overhead_q1_under_5pct(tpch_dir):
    """Warm q1 with the registry ON stays within 5% of OFF — the
    drift-cancelling scheme (alternating interleaved samples, medians,
    retries) from the PR-1 gates. The warm path performs no unify at
    all; this pins that the plane stays off it."""
    from benchmarks.tpch.schema_def import register_tpch

    ctx = BallistaContext.standalone()
    register_tpch(ctx, tpch_dir, "tbl")
    qdir = os.path.join(REPO, "benchmarks", "tpch", "queries")
    df = ctx.sql(open(os.path.join(qdir, "q1.sql")).read())
    df.collect()  # warm: jit compile + table caches

    def set_enabled(on: bool):
        if on:
            os.environ.pop("BALLISTA_DICT_REGISTRY", None)
        else:
            os.environ["BALLISTA_DICT_REGISTRY"] = "off"

    def sample(on: bool):
        set_enabled(on)
        t0 = time.perf_counter()
        for _ in range(3):
            df.collect()
        return time.perf_counter() - t0

    try:
        sample(True)
        sample(False)

        def measure():
            offs, ons = [], []
            for i in range(9):
                if i % 2 == 0:
                    offs.append(sample(False))
                    ons.append(sample(True))
                else:
                    ons.append(sample(True))
                    offs.append(sample(False))
            return sorted(offs)[4], sorted(ons)[4]

        for _attempt in range(3):
            t_off, t_on = measure()
            if t_on <= t_off * 1.05 + 2e-3:
                break
        else:
            overhead = (t_on - t_off) / t_off
            raise AssertionError(
                f"dictionary-registry overhead {overhead:.1%} "
                f"(on={t_on:.4f}s off={t_off:.4f}s)")
    finally:
        set_enabled(True)


# ---------------------------------------------------------------------------
# tooling
# ---------------------------------------------------------------------------


def test_dict_sites_lint_clean():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "dev", "check_dict_sites.py")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr


def test_dict_sites_lint_detects(tmp_path):
    # the lint actually fires on a host unify site outside the registry
    # (staged tree ships the shim + the analysis engine it runs on; the
    # engine loads standalone, so no ballista_tpu/__init__ is needed)
    import shutil

    stage = tmp_path / "repo"
    (stage / "dev").mkdir(parents=True)
    pkg = stage / "ballista_tpu"
    pkg.mkdir()
    (pkg / "rogue.py").write_text(
        "import numpy as np\n"
        "def unify(dicts):\n"
        "    return np.unique(np.concatenate(dicts))\n")
    for f in ("check_dict_sites.py", "analyze.py"):
        shutil.copy(os.path.join(REPO, "dev", f), stage / "dev" / f)
    shutil.copytree(os.path.join(REPO, "ballista_tpu", "analysis"),
                    pkg / "analysis",
                    ignore=shutil.ignore_patterns("__pycache__"))
    r = subprocess.run(
        [sys.executable, str(stage / "dev" / "check_dict_sites.py")],
        capture_output=True, text=True)
    assert r.returncode == 1 and "rogue.py" in r.stderr
