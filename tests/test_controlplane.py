"""Durable elastic control plane (docs/robustness.md "Durability &
elasticity"): restart-safe scheduler state, persistent admission queue,
demand-driven autoscaler, cost feedback.

Covers the journal (submission records, planned marker, degrade-loudly
posture), the cost-feedback store (EWMA fold, partition/threshold
advice, explicit-settings precedence), the autoscaler decision loop
(fleet bounds, cooldown, idle drain, spawn fault point), the recovery
pass (in-flight resume, queued restore in priority order, orphan
fail-loudly), sqlite crash atomicity (kill -9 a writer mid-batch, no
torn rows), the process-level restart chaos gate (SIGKILL the scheduler
binary with queued + running jobs, restart against the same sqlite
file, byte-identical results, zero hangs), and the <5% warm-submission
overhead gate with durability on.

Style: service-level tests use direct calls like test_admission.py;
the chaos gate runs the real binaries via tests/procutil.
"""

import os
import pickle
import re
import signal
import socket
import time

import numpy as np
import pytest

from ballista_tpu import Int64, Utf8, col, schema, serde, sum_
from ballista_tpu.distributed.controlplane import (
    Autoscaler,
    AutoscalerConfig,
    ControlPlaneJournal,
    CostFeedbackStore,
    SubprocessExecutorLauncher,
)
from ballista_tpu.distributed.controlplane.costs import _stage_costs
from ballista_tpu.distributed.scheduler import SchedulerService
from ballista_tpu.distributed.state import (
    MemoryBackend,
    SchedulerState,
    SqliteBackend,
)
from ballista_tpu.distributed.types import JobStatus
from ballista_tpu.io import TblSource
from ballista_tpu.logical import LogicalPlanBuilder
from ballista_tpu.physical.planner import PlannerOptions
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.testing.faults import reload_faults
from tests.procutil import spawn_module, spawn_script

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

TSCHEMA = schema(("a", Int64), ("c", Utf8))
N_ROWS = 120


@pytest.fixture
def faults_env():
    saved = os.environ.get("BALLISTA_FAULTS")

    def arm(spec: str):
        if spec:
            os.environ["BALLISTA_FAULTS"] = spec
        else:
            os.environ.pop("BALLISTA_FAULTS", None)
        reload_faults()

    yield arm
    if saved is None:
        os.environ.pop("BALLISTA_FAULTS", None)
    else:
        os.environ["BALLISTA_FAULTS"] = saved
    reload_faults()


def _write_tbl(tmp_path, rows: int = N_ROWS, parts: int = 2) -> str:
    d = tmp_path / "t"
    d.mkdir(exist_ok=True)
    for part in range(parts):
        lines = [f"{i}|k{i % 7}|" for i in range(rows) if i % parts == part]
        (d / f"part{part}.tbl").write_text("\n".join(lines) + "\n")
    return str(d)


def _submit(svc, src, settings=None, deadline_secs: float = 0.0):
    plan = (LogicalPlanBuilder.scan("t", src)
            .aggregate([col("c")], [sum_(col("a")).alias("s")])
            .build())
    params = pb.ExecuteQueryParams()
    params.logical_plan.CopyFrom(serde.plan_to_proto(plan))
    for k, v in (settings or {}).items():
        params.settings[k] = v
    if deadline_secs:
        params.deadline_secs = deadline_secs
    return svc.ExecuteQuery(params)


def _wait_until(cond, timeout: float, msg: str):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(msg)


class _BrokenKv:
    """A KvBackend whose every operation raises (degrade posture)."""

    def __getattr__(self, name):
        def boom(*a, **k):
            raise OSError("backend unreachable")
        return boom


# ---------------------------------------------------------------------------
# (a) journal: submission records, planned marker, degradation
# ---------------------------------------------------------------------------


def test_journal_roundtrip():
    st = SchedulerState(MemoryBackend())
    j = ControlPlaneJournal(st)
    j.record_submission("j1", "sess-a", {"k": "v"}, sql="select 1",
                        catalog=[b"ct"], action="queue",
                        reason="saturated", priority=2.0,
                        deadline_ts=123.0, enqueued_at=10.0)
    j.record_submission("j2", "sess-b", {}, plan_bytes=b"plan",
                        action="admit", enqueued_at=5.0)
    subs = j.submissions()
    # oldest first
    assert [e["job_id"] for e in subs] == ["j2", "j1"]
    e1 = subs[1]
    assert e1["session_id"] == "sess-a"
    assert e1["settings"] == {"k": "v"}
    assert e1["sql"] == "select 1"
    assert e1["catalog"] == [b"ct"]
    assert e1["action"] == "queue"
    assert e1["priority"] == 2.0
    assert e1["deadline_ts"] == 123.0
    assert subs[0]["plan_bytes"] == b"plan"

    assert not j.is_planned("j2")
    j.mark_planned("j2")
    assert j.is_planned("j2")

    j.drop_submission("j2")
    assert [e["job_id"] for e in j.submissions()] == ["j1"]
    assert not j.is_planned("j2")
    assert not j.degraded


def test_journal_degrades_loudly_never_raises(caplog):
    st = SchedulerState(MemoryBackend())
    st.kv = _BrokenKv()
    j = ControlPlaneJournal(st)
    # every operation is a guarded no-op, not an exception
    j.record_submission("j1", "s", {})
    assert j.submissions() == []
    j.mark_planned("j1")
    assert not j.is_planned("j1")
    j.drop_submission("j1")
    assert j.degraded


def test_journal_skips_torn_records():
    st = SchedulerState(MemoryBackend())
    j = ControlPlaneJournal(st)
    j.record_submission("good", "s", {}, enqueued_at=1.0)
    # a torn (half-written) record must not take the scan down
    st.kv.put(st._k("cpq", "torn"), b"\x80\x04not a pickle")
    assert [e["job_id"] for e in j.submissions()] == ["good"]


# ---------------------------------------------------------------------------
# (b) cost feedback: observe -> advise
# ---------------------------------------------------------------------------


def _fake_metrics(shuffle_bytes: int, stages: int = 2) -> dict:
    sm = {}
    for sid in range(1, stages + 1):
        ops = []
        if sid < stages:  # non-final stages wrote shuffle output
            ops.append({"operator": "ShuffleWrite",
                        "metrics": {"bytes_written":
                                    shuffle_bytes // max(stages - 1, 1)}})
        sm[sid] = {"elapsed_total": 0.5, "operators": ops}
    return sm


def test_stage_costs_counts_nonfinal_shuffle_writes():
    task_secs, shuffle = _stage_costs(_fake_metrics(1000, stages=3))
    assert shuffle == 1000
    assert task_secs == pytest.approx(1.5)


def test_cost_observe_ewma_and_lookup():
    store = CostFeedbackStore(SchedulerState(MemoryBackend()))
    r1 = store.observe("digest-a", _fake_metrics(1000), wall_seconds=2.0)
    assert r1["runs"] == 1 and r1["shuffle_bytes"] == 1000
    r2 = store.observe("digest-a", _fake_metrics(3000), wall_seconds=4.0)
    assert r2["runs"] == 2
    # EWMA(alpha=.5): halfway between old and new
    assert r2["shuffle_bytes"] == pytest.approx(2000)
    assert r2["wall_seconds"] == pytest.approx(3.0)
    assert store.lookup("digest-a")["runs"] == 2
    assert store.lookup("missing") is None


def test_cost_advise_sizes_partitions_and_threshold():
    store = CostFeedbackStore(SchedulerState(MemoryBackend()))
    target = 1024
    settings = {"controlplane.cost_target_partition_bytes": str(target)}
    # large observed shuffle: partitions sized to ~target bytes each,
    # threshold lowered (prefer the co-partitioned join)
    store.observe("big", _fake_metrics(16 * target))
    opts, notes = store.advise("big", PlannerOptions(), settings)
    assert opts.join_partitions == 16
    assert opts.join_partition_threshold == 1_000_000 // 4
    assert opts.cost_notes == tuple(notes) and notes
    # tiny observed shuffle: threshold raised (prefer merged build)
    store.observe("small", _fake_metrics(100))
    opts, notes = store.advise("small", PlannerOptions(), settings)
    assert opts.join_partition_threshold == 4_000_000
    assert any("broadcast" in n for n in notes)


def test_cost_advise_respects_explicit_settings_and_off_knob():
    store = CostFeedbackStore(SchedulerState(MemoryBackend()))
    settings = {"controlplane.cost_target_partition_bytes": "1024"}
    store.observe("d", _fake_metrics(16 * 1024))
    # explicit client knobs always win
    opts, notes = store.advise(
        "d", PlannerOptions(),
        {**settings, "join.partitions": "8",
         "join.partitioned.threshold": "1000000"})
    assert opts.join_partitions == 8
    assert opts.join_partition_threshold == 1_000_000
    # feedback off: untouched even without explicit knobs
    opts, notes = store.advise(
        "d", PlannerOptions(),
        {**settings, "controlplane.cost_feedback": "off"})
    assert opts.join_partitions == 8 and notes == []
    # no history: untouched
    opts, notes = store.advise("unknown", PlannerOptions(), settings)
    assert opts.join_partitions == 8 and notes == []


def test_cost_store_degrades_to_noop():
    st = SchedulerState(MemoryBackend())
    st.kv = _BrokenKv()
    store = CostFeedbackStore(st)
    store.observe("d", _fake_metrics(1000))
    opts, notes = store.advise("d", PlannerOptions(), {})
    assert opts.join_partitions == 8 and notes == []


def test_explain_renders_cost_feedback_row(tmp_path):
    from ballista_tpu.execution import plan_logical
    from ballista_tpu.logical import Explain

    src = TblSource(_write_tbl(tmp_path, rows=8, parts=1), TSCHEMA)
    scan = LogicalPlanBuilder.scan("t", src).build()
    opts = PlannerOptions(cost_notes=("join.partitions 8 -> 16",))
    rows = dict(plan_logical(Explain(scan), opts).rows)
    assert "cost_feedback" in rows
    assert "join.partitions 8 -> 16" in rows["cost_feedback"]
    # without notes, no extra row
    rows = dict(plan_logical(Explain(scan), PlannerOptions()).rows)
    assert "cost_feedback" not in rows


# ---------------------------------------------------------------------------
# (c) autoscaler: config, decision loop, fault point
# ---------------------------------------------------------------------------


def test_autoscaler_config_resolution():
    cfg = AutoscalerConfig.from_settings(
        {"autoscale.enabled": "on", "autoscale.max_executors": "9"},
        env={"BALLISTA_AUTOSCALE_MIN_EXECUTORS": "2",
             "BALLISTA_AUTOSCALE_MAX_EXECUTORS": "4"})
    assert cfg.enabled and cfg.min_executors == 2
    assert cfg.max_executors == 9  # settings beat env
    with pytest.raises(ValueError, match="exceeds"):
        AutoscalerConfig.from_settings({"autoscale.min_executors": "5",
                                        "autoscale.max_executors": "2"})
    with pytest.raises(ValueError, match="number"):
        AutoscalerConfig.from_settings({"autoscale.backlog_tasks": "lots"})


class _Hooks:
    def __init__(self):
        self.spawned = 0
        self.drained = []

    def spawn(self):
        self.spawned += 1

    def drain(self):
        self.drained.append(f"e{len(self.drained)}")
        return self.drained[-1]


def _scaler(sig, hooks, **cfg_kw):
    cfg = AutoscalerConfig(enabled=True, **cfg_kw)
    return Autoscaler(cfg, lambda: sig, hooks.spawn, hooks.drain)


def test_autoscaler_scales_up_on_backlog_within_bounds():
    sig = {"backlog": 10, "inflight": 0, "executors": 1,
           "eta_seconds": 0.0}
    h = _Hooks()
    a = _scaler(sig, h, min_executors=1, max_executors=3,
                backlog_tasks=8, cooldown_secs=100.0)
    assert a.tick(now=1000.0) == "scale-up"
    assert h.spawned == 1 and a.target == 2
    # cooldown holds the next tick even with backlog
    assert a.tick(now=1001.0) is None
    # cooled, but at max: hold
    sig["executors"] = 3
    assert a.tick(now=2000.0) is None
    assert h.spawned == 1


def test_autoscaler_min_floor_ignores_cooldown():
    sig = {"backlog": 0, "inflight": 0, "executors": 0,
           "eta_seconds": 0.0}
    h = _Hooks()
    a = _scaler(sig, h, min_executors=2, max_executors=4,
                cooldown_secs=1000.0)
    assert a.tick(now=1.0) == "scale-up"
    assert a.tick(now=1.5) == "scale-up"  # still below min: no cooldown
    assert h.spawned == 2
    rows = a.decision_rows()
    assert all(r["reason"] == "min-floor" for r in rows)


def test_autoscaler_eta_trigger():
    sig = {"backlog": 1, "inflight": 1, "executors": 1,
           "eta_seconds": 50.0}
    h = _Hooks()
    a = _scaler(sig, h, min_executors=1, max_executors=3,
                backlog_tasks=100, eta_secs=30.0, cooldown_secs=0.0)
    assert a.tick(now=1.0) == "scale-up"
    assert a.decision_rows()[-1]["reason"] == "eta"


def test_autoscaler_drains_idle_down_to_min():
    sig = {"backlog": 0, "inflight": 0, "executors": 3,
           "eta_seconds": 0.0}
    h = _Hooks()
    a = _scaler(sig, h, min_executors=1, max_executors=4,
                cooldown_secs=0.0, idle_secs=10.0)
    assert a.tick(now=100.0) is None  # idle clock starts
    assert a.tick(now=105.0) is None  # not idle long enough
    assert a.tick(now=111.0) == "scale-down"
    assert h.drained == ["e0"]
    # busy resets the idle clock
    sig["inflight"] = 1
    assert a.tick(now=130.0) is None
    sig["inflight"] = 0
    assert a.tick(now=131.0) is None
    # at the min floor: never drains below
    sig["executors"] = 1
    assert a.tick(now=500.0) is None
    assert len(h.drained) == 1
    rows = a.decision_rows()
    assert rows[-1]["action"] == "scale-down"
    assert rows[-1]["drained"] == "e0"


def test_autoscaler_spawn_fault_skips_tick(faults_env):
    sig = {"backlog": 10, "inflight": 0, "executors": 1,
           "eta_seconds": 0.0}
    h = _Hooks()
    a = _scaler(sig, h, min_executors=1, max_executors=4,
                backlog_tasks=1, cooldown_secs=0.0)
    faults_env("autoscaler.spawn=fail-once")
    try:
        # triggered fault: the tick is skipped, nothing spawned
        assert a.tick(now=1.0) is None
        assert h.spawned == 0
        # the demand signal persists; the next tick retries and lands
        assert a.tick(now=2.0) == "scale-up"
        assert h.spawned == 1
    finally:
        faults_env("")


def test_autoscaler_rows_in_system_table():
    svc = SchedulerService(SchedulerState(MemoryBackend()))
    try:
        assert svc.systables.table_rows("system.autoscaler") == []
        sig = {"backlog": 10, "inflight": 0, "executors": 0,
               "eta_seconds": 0.0}
        svc.attach_autoscaler(
            AutoscalerConfig(enabled=True, min_executors=1,
                             max_executors=2, backlog_tasks=1),
            spawn_fn=lambda: None, drain_fn=lambda: None, start=False)
        svc.autoscaler.signal_fn = lambda: sig
        assert svc.autoscaler.tick(now=1.0) == "scale-up"
        rows = svc.systables.table_rows("system.autoscaler")
        assert rows and rows[-1]["action"] == "scale-up"
        assert rows[-1]["reason"] == "min-floor"
        # the decision counters ride /metrics
        names = [s[0] for s in svc._metric_samples()]
        assert "ballista_autoscale_target_executors" in names
    finally:
        svc.close_health()


def test_autoscaler_backlog_counts_only_admittable_queue():
    """Scale-up demand consults per-session admission quotas: a pile of
    jobs queued behind ONE tenant's max_session_jobs must not buy
    executors no quota would let it use, while multi-tenant backlog
    still counts in full."""
    from ballista_tpu.distributed.admission import AdmissionController

    ctl = AdmissionController(state=None)
    s1 = {"session.id": "s1", "admission.max_session_jobs": "2"}
    assert ctl.gate("j1", s1).action == "admit"
    assert ctl.gate("j2", s1).action == "admit"
    # five more from the same session: all queue, but ZERO are
    # admittable — s1 already holds its two slots
    for i in range(5):
        assert ctl.gate(f"jq{i}", s1).action == "queue"
    assert ctl.queue_depth() == 5
    assert ctl.admittable_queue_depth() == 0

    # a second tenant queued on CLUSTER concurrency is real demand
    s2 = {"session.id": "s2", "admission.max_running_jobs": "2"}
    assert ctl.gate("k1", s2).action == "queue"
    assert ctl.queue_depth() == 6
    assert ctl.admittable_queue_depth() == 1

    # virtual slots: a freed s1 slot makes exactly ONE of the five
    # queued s1 jobs admittable, not all five
    ctl.on_terminal("j1")
    assert ctl.admittable_queue_depth() == 2

    # unquota'd sessions always count in full
    s3 = {"session.id": "s3", "admission.max_running_jobs": "1"}
    assert ctl.gate("m1", s3).action == "queue"
    assert ctl.admittable_queue_depth() == 3

    # the scheduler's autoscaler signal uses the admittable variant
    svc = SchedulerService(SchedulerState(MemoryBackend()))
    try:
        svc.attach_autoscaler(
            AutoscalerConfig(enabled=True, min_executors=0,
                             max_executors=2, backlog_tasks=1),
            spawn_fn=lambda: None, drain_fn=lambda: None, start=False)
        sess = {"session.id": "t1", "admission.max_session_jobs": "1",
                "admission.enabled": "on"}
        assert svc.admission.gate("b1", sess).action == "admit"
        assert svc.admission.gate("b2", sess).action == "queue"
        assert svc.admission.queue_depth() == 1
        # quota-blocked backlog is invisible to the scaling signal
        assert svc.autoscaler.signal_fn()["backlog"] == 0
    finally:
        svc.close_health()


def test_subprocess_launcher_spawn_and_drain(tmp_path):
    # against a dead port: the executor binary starts, backs off, and
    # SIGTERM drains it — the launcher only manages processes
    launcher = SubprocessExecutorLauncher(
        "127.0.0.1", 1,  # nothing listens on port 1
        extra_args=["--work-dir", str(tmp_path / "w")],
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")})
    try:
        p = launcher.spawn()
        assert launcher.alive() == 1
        pid = launcher.drain()
        assert pid == str(p.pid)
        p.wait(timeout=30)
        assert launcher.alive() == 0
        assert launcher.drain() is None
    finally:
        launcher.stop_all()


# ---------------------------------------------------------------------------
# (d) recovery pass (in-process, direct service calls)
# ---------------------------------------------------------------------------


QUEUE_SETTINGS = {
    "admission.max_running_jobs": "1",
    "admission.queue_timeout_secs": "300",
}


def _wait_planned(svc, job_id, timeout=15.0):
    _wait_until(lambda: svc.journal.is_planned(job_id), timeout,
                f"job {job_id} never finished planning")


def test_recover_inflight_and_queued_priority_order(tmp_path):
    db = str(tmp_path / "state.db")
    src = TblSource(_write_tbl(tmp_path), TSCHEMA)
    svc = SchedulerService(SchedulerState(SqliteBackend(db)))
    try:
        # A admits (and plans); B and C queue behind the 1-job bound,
        # C at higher priority
        ja = _submit(svc, src, QUEUE_SETTINGS).job_id
        _wait_planned(svc, ja)
        jb = _submit(svc, src, {**QUEUE_SETTINGS,
                                "admission.priority": "1"}).job_id
        jc = _submit(svc, src, {**QUEUE_SETTINGS,
                                "admission.priority": "5"}).job_id
        assert svc.admission.queue_depth() == 2
    finally:
        svc.close_health()
    # no shutdown: the scheduler "crashed" with A in flight, B+C queued

    svc2 = SchedulerService(SchedulerState(SqliteBackend(db)))
    try:
        rep = svc2.recover()
        assert rep.jobs_seen == 3
        assert rep.jobs_inflight == 1
        assert rep.queued_restored == 2
        assert rep.relaunched == 0 and rep.orphans_failed == 0
        assert rep.recovered_jobs == 3
        assert not rep.errors
        # A's tasks are back on the ready queue
        assert rep.tasks_requeued > 0
        # priority order survived the restart: C pops before B
        info_c = svc2.admission.queue_info(jc)
        info_b = svc2.admission.queue_info(jb)
        assert info_c["queue_position"] == 1
        assert info_b["queue_position"] == 2
        assert info_c["recovered"] and info_b["recovered"]
        # ... and GetJobStatus surfaces the marker
        st = svc2.GetJobStatus(pb.GetJobStatusParams(job_id=jc))
        assert st.status.WhichOneof("status") == "queued"
        assert st.status.queued.recovered
        # A re-occupied its admission slot: the pump must not launch
        # B/C past max_running_jobs=1
        svc2.admission.pump(force=True)
        assert svc2.admission.queue_depth() == 2
        # recovery is idempotent
        rep2 = svc2.recover()
        assert rep2.queued_restored == 2 and not rep2.errors
        assert svc2.admission.queue_depth() == 2
    finally:
        svc2.close_health()


def test_recover_replays_planning_lost_midflight(tmp_path):
    """An ADMITTED job whose scheduler died before the planned marker
    landed: partial stage rows are wiped and planning replays from the
    journaled submission."""
    db = str(tmp_path / "state.db")
    src = TblSource(_write_tbl(tmp_path), TSCHEMA)
    svc = SchedulerService(SchedulerState(SqliteBackend(db)))
    try:
        ja = _submit(svc, src).job_id
        _wait_planned(svc, ja)
        # simulate the crash window: planned marker never landed, and a
        # partial stage set is on disk
        svc.state.kv.delete(svc.state._k("cpplanned", ja))
    finally:
        svc.close_health()

    svc2 = SchedulerService(SchedulerState(SqliteBackend(db)))
    try:
        rep = svc2.recover()
        assert rep.relaunched == 1 and not rep.errors
        # planning replayed to completion: full stage set + marker
        _wait_planned(svc2, ja)
        assert svc2.state.stage_ids(ja)
    finally:
        svc2.close_health()


def test_recover_fails_orphans_loudly(tmp_path):
    """A non-terminal job with neither stages nor a journal record gets
    a terminal failed status (client sees an answer, not a hang)."""
    db = str(tmp_path / "state.db")
    st = SchedulerState(SqliteBackend(db))
    st.save_job_status("orphan1", JobStatus("queued"))
    svc = SchedulerService(st)
    try:
        # drop the journal record the submit path would have written
        svc.journal.drop_submission("orphan1")
        rep = svc.recover()
        assert rep.orphans_failed == 1
        got = st.get_job_status("orphan1")
        assert got.state == "failed"
        assert "scheduler restart" in got.error
    finally:
        svc.close_health()


def test_recover_resets_unroutable_completed_outputs(tmp_path):
    """A completed task whose producing executor left no durable
    address record cannot serve its shuffle outputs — recovery resets
    it instead of letting consumers hit fetch failures."""
    from ballista_tpu.distributed.types import (ExecutorMeta, PartitionId,
                                                TaskStatus)

    db = str(tmp_path / "state.db")
    st = SchedulerState(SqliteBackend(db))
    st.save_job_status("j1", JobStatus("queued"))
    st.save_stage_plan("j1", 1, b"x", 1, [])
    st.save_stage_plan("j1", 2, b"y", 1, [1])
    st.save_task_status(TaskStatus(PartitionId("j1", 1, 0)))
    st.save_task_status(TaskStatus(PartitionId("j1", 2, 0)))
    st.enqueue_job("j1")
    st.save_executor_metadata(ExecutorMeta("gone", "h", 1))
    t = st.next_task()
    st.task_completed(TaskStatus(t, "completed", executor_id="gone",
                                 path="p", stats={}))
    # the producer's address record vanishes (never-registered executor
    # after a restart): its completed output is unroutable
    st.kv.delete(st._k("executors_meta", "gone"))
    st.kv.delete(st._k("executors", "gone"))

    svc = SchedulerService(SchedulerState(SqliteBackend(db)))
    try:
        svc.journal.mark_planned("j1")
        rep = svc.recover()
        assert rep.producers_reset == 1
        # stage 1 re-queued, stage 2 pulled back
        assert svc.state.next_task().stage_id == 1
    finally:
        svc.close_health()


def test_recover_noop_on_fresh_state():
    svc = SchedulerService(SchedulerState(MemoryBackend()))
    try:
        rep = svc.recover()
        assert rep.jobs_seen == 0 and rep.recovered_jobs == 0
        assert not rep.errors
    finally:
        svc.close_health()


# ---------------------------------------------------------------------------
# (e) sqlite crash atomicity: kill -9 a writer mid-batch, no torn rows
# ---------------------------------------------------------------------------


_TORN_WRITER = """
import pickle, sys, time
sys.path.insert(0, {repo!r})
from ballista_tpu.distributed.state import SqliteBackend
kv = SqliteBackend({db!r})
print("writer ready", flush=True)
i = 0
while True:
    # one record per put: committed-or-absent is the contract under
    # SIGKILL; the value carries its own checksum
    payload = {{"seq": i, "blob": b"x" * 4096}}
    payload["check"] = i * 31
    kv.put(f"/t/job{{i:06d}}", pickle.dumps(payload))
    if i % 50 == 0:
        print(f"wrote {{i}}", flush=True)
    i += 1
"""


@pytest.mark.slow
def test_sqlite_torn_write_crash_atomicity(tmp_path):
    db = str(tmp_path / "crash.db")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = spawn_script(
        ["-c", _TORN_WRITER.format(repo=REPO, db=db)], env)
    try:
        proc.wait_for(lambda ln: "wrote 200" in ln, timeout=60)
    finally:
        # SIGKILL mid-batch: no atexit, no flush, no rollback chance
        proc.popen.kill()
        proc.wait_exit(timeout=30)

    kv = SqliteBackend(db)
    rows = kv.get_from_prefix("/t/")
    assert len(rows) >= 200
    seqs = []
    for k, v in rows:
        rec = pickle.loads(v)  # a torn row would fail to unpickle
        assert rec["check"] == rec["seq"] * 31, f"corrupt row {k}"
        assert len(rec["blob"]) == 4096
        seqs.append(rec["seq"])
    # committed prefix: every row below the max survived whole
    assert sorted(seqs) == list(range(len(seqs)))
    # the crash-atomicity pragmas are actually set on fresh connections
    c = kv._conn()
    assert c.execute("PRAGMA journal_mode").fetchone()[0] == "wal"
    assert c.execute("PRAGMA synchronous").fetchone()[0] == 2  # FULL


# ---------------------------------------------------------------------------
# (f) restart chaos: SIGKILL the scheduler binary, recover, finish
# ---------------------------------------------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _poll_status(host, port, job_id, timeout=120.0):
    """GetJobStatus until terminal, retrying through scheduler
    downtime; returns the terminal result (zero-hang gate: bounded)."""
    from ballista_tpu.distributed.scheduler import SchedulerClient

    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            client = SchedulerClient(host, port)
            try:
                r = client.GetJobStatus(
                    pb.GetJobStatusParams(job_id=job_id))
            finally:
                client.close()
            which = r.status.WhichOneof("status")
            if which in ("completed", "failed", "cancelled"):
                return r
        except Exception:  # noqa: BLE001 - scheduler restarting
            pass
        time.sleep(0.2)
    raise AssertionError(f"job {job_id} not terminal in {timeout}s")


@pytest.mark.slow
def test_scheduler_restart_chaos_end_to_end(tmp_path):
    """The PR's e2e gate: SIGKILL the scheduler binary with one
    admitted in-flight job and two queued jobs, restart it against the
    same sqlite file, and every job completes with results identical to
    an unfaulted run — queued jobs keeping their priority order, zero
    hangs (every wait is bounded)."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.distributed.client import (submit_sql,
                                                 _fetch_result_frames)
    from ballista_tpu.sql.planner import CatalogTable

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    data = _write_tbl(tmp_path, rows=N_ROWS, parts=2)
    db = str(tmp_path / "sched-state.db")
    port = _free_port()
    sched_args = ["ballista_tpu.distributed.scheduler_main",
                  "--bind-host", "127.0.0.1", "--port", str(port),
                  "--state", f"sqlite:{db}", "--metrics-port=-1"]

    procs = []
    queries = [
        ("select c, sum(a) as s from t group by c order by c", "0"),
        ("select c, count(*) as n from t group by c order by c", "1"),
        ("select c, min(a) as m from t group by c order by c", "5"),
    ]

    def catalog():
        return {"t": CatalogTable("t", TblSource(data, TSCHEMA))}

    try:
        sched = spawn_module(sched_args, env)
        procs.append(sched)
        sched.wait_for(lambda ln: "listening on" in ln)
        sched.wait_for(lambda ln: "recovered_jobs=" in ln)

        # submit with NO executors: job 0 admits and plans (in-flight),
        # jobs 1+2 queue behind max_running_jobs=1, priorities 1 and 5
        job_ids = []
        for sql, prio in queries:
            settings = {**QUEUE_SETTINGS, "admission.priority": prio,
                        "session.id": "chaos"}
            job_ids.append(submit_sql("127.0.0.1", port, sql,
                                      catalog(), settings))
        # wait until job 0's planning landed durably (stage rows exist)
        st_probe = SchedulerState(SqliteBackend(db))
        _wait_until(lambda: bool(st_probe.stage_ids(job_ids[0])), 30,
                    "job 0 never planned")

        # crash: SIGKILL — no drain, no cleanup
        sched.popen.send_signal(signal.SIGKILL)
        sched.wait_exit(timeout=30)

        # restart against the same sqlite file
        sched2 = spawn_module(sched_args, env)
        procs.append(sched2)
        line = sched2.wait_for(lambda ln: "recovered_jobs=" in ln)
        m = re.search(r"recovered_jobs=(\d+).*queued_restored=(\d+)"
                      r".*inflight=(\d+)", line)
        assert m, line
        assert int(m.group(1)) == 3
        assert int(m.group(2)) == 2
        assert int(m.group(3)) == 1

        # queued jobs kept their priority order across the restart:
        # job 2 (priority 5) ahead of job 1 (priority 1), both marked
        from ballista_tpu.distributed.scheduler import SchedulerClient

        client = SchedulerClient("127.0.0.1", port)
        try:
            s2 = client.GetJobStatus(
                pb.GetJobStatusParams(job_id=job_ids[2]))
            s1 = client.GetJobStatus(
                pb.GetJobStatusParams(job_id=job_ids[1]))
        finally:
            client.close()
        assert s2.status.queued.queue_position == 1
        assert s1.status.queued.queue_position == 2
        assert s2.status.queued.recovered and s1.status.queued.recovered

        # now give the cluster an executor; every job must complete
        ex = spawn_module(["ballista_tpu.distributed.executor_main",
                           "--scheduler-host", "127.0.0.1",
                           "--scheduler-port", str(port),
                           "--work-dir", str(tmp_path / "w0"),
                           "--concurrent-tasks", "1",
                           "--metrics-port=-1"], env)
        procs.append(ex)

        results = {}
        for jid, (sql, _p) in zip(job_ids, queries):
            r = _poll_status("127.0.0.1", port, jid)
            assert r.status.WhichOneof("status") == "completed", (
                f"{sql!r}: {r}")
            results[sql] = _fetch_result_frames(r)

        # byte-identical to an unfaulted run (standalone engine, same
        # queries, same data)
        ctx = BallistaContext.standalone()
        ctx.register_source("t", TblSource(data, TSCHEMA))
        for sql, _p in queries:
            exp = ctx.sql(sql).collect()
            got = results[sql]
            assert list(got.columns) == list(exp.columns)
            for name in exp.columns:
                assert np.array_equal(got[name].to_numpy(),
                                      exp[name].to_numpy()), (
                    f"{sql!r} column {name} diverged after restart")
    finally:
        for p in procs:
            if p.poll() is None:
                p.popen.send_signal(signal.SIGKILL)
        for p in procs:
            try:
                p.wait_exit(timeout=20)
            except Exception:  # noqa: BLE001 - teardown
                pass


# ---------------------------------------------------------------------------
# (g) autoscaler e2e over a LocalCluster: burst -> grow -> drain
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_autoscaler_localcluster_burst(tmp_path):
    """Demand-driven elasticity in-process: a backlog burst grows the
    fleet within [min,max]; idle drains it back to min. Decisions land
    in system.autoscaler."""
    from ballista_tpu.distributed.executor import LocalCluster

    cluster = LocalCluster(num_executors=1, concurrent_tasks=1)
    try:
        svc = cluster.service
        svc.attach_autoscaler(
            AutoscalerConfig(enabled=True, min_executors=1,
                             max_executors=2, backlog_tasks=2,
                             cooldown_secs=0.0, idle_secs=0.2),
            spawn_fn=cluster.add_executor,
            drain_fn=cluster.remove_executor,
            start=False)
        # synthetic backlog signal: deterministic, no real queue race
        sig = {"backlog": 5, "inflight": 1, "executors": 1,
               "eta_seconds": 0.0}
        svc.autoscaler.signal_fn = lambda: sig
        assert svc.autoscaler.tick(now=1.0) == "scale-up"
        assert len(cluster.executors) == 2
        sig.update(executors=2)
        assert svc.autoscaler.tick(now=2.0) is None  # at max
        # drain back once idle
        sig.update(backlog=0, inflight=0)
        svc.autoscaler.tick(now=10.0)   # idle clock starts
        assert svc.autoscaler.tick(now=10.5) == "scale-down"
        assert len(cluster.executors) == 1
        sig.update(executors=1)
        assert svc.autoscaler.tick(now=20.0) is None  # min floor
        actions = [r["action"] for r in
                   svc.systables.table_rows("system.autoscaler")]
        assert actions == ["scale-up", "scale-down"]
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# (h) overhead gate: durability on the submit path costs < 5%
# ---------------------------------------------------------------------------


def test_durability_overhead_under_5pct(tmp_path):
    """Drift-cancelling gate on the hot path the journal sits on
    (ExecuteQuery -> planned): sqlite-backed durable submissions vs the
    same service with the journal degraded to no-op, interleaved
    alternating samples + medians, <5% (+2ms floor) or fail."""
    db = str(tmp_path / "ovh.db")
    svc = SchedulerService(SchedulerState(SqliteBackend(db)))
    src = TblSource(_write_tbl(tmp_path, rows=8, parts=1), TSCHEMA)

    def cycle():
        r = _submit(svc, src, {"session.id": "ovh"})
        assert not r.error
        deadline = time.time() + 10
        while not svc.state.stage_ids(r.job_id):
            assert time.time() < deadline, "planning never finished"
            time.sleep(0.001)
        svc.CancelJob(pb.CancelJobParams(job_id=r.job_id))

    class _NoopJournal(ControlPlaneJournal):
        def record_submission(self, *a, **k):
            pass

        def mark_planned(self, job_id):
            pass

        def drop_submission(self, job_id):
            pass

    real = svc.journal
    noop = _NoopJournal(svc.state)

    def sample(on: bool) -> float:
        svc.journal = real if on else noop
        t0 = time.perf_counter()
        for _ in range(3):
            cycle()
        return time.perf_counter() - t0

    sample(True)
    sample(False)  # settle both paths

    def measure():
        offs, ons = [], []
        for i in range(9):
            if i % 2 == 0:
                offs.append(sample(False))
                ons.append(sample(True))
            else:
                ons.append(sample(True))
                offs.append(sample(False))
        return sorted(offs)[4], sorted(ons)[4]

    try:
        for _ in range(3):
            t_off, t_on = measure()
            if t_on <= t_off * 1.05 + 2e-3:
                return
        overhead = (t_on - t_off) / t_off
        raise AssertionError(
            f"durability overhead {overhead:.1%} "
            f"(on={t_on:.4f}s off={t_off:.4f}s)")
    finally:
        svc.journal = real
        svc.close_health()
