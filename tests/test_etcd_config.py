"""HA state backend (etcd v3 wire protocol) + layered config files.

(reference: rust/scheduler/src/state/etcd.rs:29-113 — get/prefix/
put-with-lease/distributed-lock; configure_me TOML layering in
scheduler/main.rs:65-66.) No etcd binary exists in this environment, so
the backend is exercised against an in-process fake speaking the same
wire protocol."""

import threading
import time

import pytest

from ballista_tpu.distributed.config import layered_config
from ballista_tpu.distributed.etcd import (
    EtcdBackend,
    FakeEtcdServer,
    prefix_range_end,
)
from ballista_tpu.distributed.state import SchedulerState
from ballista_tpu.distributed.types import ExecutorMeta


@pytest.fixture()
def etcd():
    server = FakeEtcdServer()
    backend = EtcdBackend(f"localhost:{server.port}")
    yield backend
    backend.close()
    server.stop()


def test_prefix_range_end():
    assert prefix_range_end(b"/a") == b"/b"
    assert prefix_range_end(b"/a\xff") == b"/b"
    assert prefix_range_end(b"\xff") == b"\0"


def test_etcd_kv_roundtrip(etcd):
    etcd.put("/ballista/ns/a", b"1")
    etcd.put("/ballista/ns/b", b"2")
    etcd.put("/other", b"3")
    assert etcd.get("/ballista/ns/a") == b"1"
    assert etcd.get("/missing") is None
    got = etcd.get_from_prefix("/ballista/ns/")
    assert got == [("/ballista/ns/a", b"1"), ("/ballista/ns/b", b"2")]
    etcd.delete("/ballista/ns/a")
    assert etcd.get("/ballista/ns/a") is None


def test_etcd_lease_expiry(etcd):
    etcd.put("/lease/k", b"v", lease_secs=1)
    assert etcd.get("/lease/k") == b"v"
    time.sleep(1.2)
    assert etcd.get("/lease/k") is None
    assert etcd.get_from_prefix("/lease/") == []


def test_etcd_distributed_lock_mutual_exclusion(etcd):
    order = []

    def worker(tag):
        with etcd.lock():
            order.append((tag, "in"))
            time.sleep(0.05)
            order.append((tag, "out"))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # critical sections never interleave
    for i in range(0, len(order), 2):
        assert order[i][0] == order[i + 1][0]
        assert order[i][1] == "in" and order[i + 1][1] == "out"


def test_lock_keepalive_outlives_ttl():
    """A critical section LONGER than the lock TTL keeps mutual
    exclusion: the background LeaseKeepAlive stream renews the lease
    (round 2 had no keepalive, so overruns silently unlocked)."""
    server = FakeEtcdServer()
    backend = EtcdBackend(f"localhost:{server.port}", lock_ttl_secs=1)
    try:
        with backend.lock() as lk:
            time.sleep(2.2)  # > 2 TTLs
            assert lk.held(), "keepalive failed to renew the lock lease"
            # the lease key must still be alive server-side
            assert server._st.alive(lk._lease)
    finally:
        backend.close()
        server.stop()


def test_lock_lost_lease_fails_loudly():
    """If the lease dies while held (etcd unreachable / revoked), the
    section must FAIL, not silently continue without mutual
    exclusion."""
    from ballista_tpu.errors import ClusterError
    from ballista_tpu.proto import etcd_pb2 as epb

    server = FakeEtcdServer()
    backend = EtcdBackend(f"localhost:{server.port}", lock_ttl_secs=1)
    try:
        with pytest.raises(ClusterError, match="mutual exclusion"):
            with backend.lock() as lk:
                # simulate lease loss (e.g. etcd leader expired it)
                backend._revoke(epb.LeaseRevokeRequest(ID=lk._lease))
                deadline = time.time() + 3
                while lk.held() and time.time() < deadline:
                    time.sleep(0.05)
                assert not lk.held(), "lost lease never detected"
    finally:
        backend.close()
        server.stop()


def test_scheduler_state_over_etcd(etcd):
    """The whole state machine runs against the etcd wire protocol."""
    state = SchedulerState(etcd, "ha")
    state.save_executor_metadata(ExecutorMeta("e1", "host1", 1234, 8))
    metas = state.get_executors_metadata()
    assert [m.id for m in metas] == ["e1"] and metas[0].num_devices == 8
    # a standby scheduler over the same etcd rehydrates the same state
    # (HA = failover; see etcd.py docstring for the active-active caveat)
    state2 = SchedulerState(etcd, "ha")
    assert [m.id for m in state2.get_executors_metadata()] == ["e1"]


# ---------------------------------------------------------------------------
# layered config
# ---------------------------------------------------------------------------


def test_layered_config_precedence(tmp_path):
    # config-file layering parses TOML via stdlib tomllib (3.11+); on
    # older interpreters with no toml parser installed the feature is
    # unavailable by design — skip instead of erroring
    pytest.importorskip("tomllib")
    cfg_file = tmp_path / "scheduler.toml"
    cfg_file.write_text('port = 6000\nnamespace = "filens"\n')
    defaults = {"port": 50050, "namespace": "default", "bind_host": "0.0.0.0"}
    # file overrides defaults
    out = layered_config("scheduler", defaults, str(cfg_file), env={})
    assert out["port"] == 6000 and out["namespace"] == "filens"
    assert out["bind_host"] == "0.0.0.0"
    # env overrides file (with type coercion)
    out = layered_config("scheduler", defaults, str(cfg_file),
                         env={"BALLISTA_SCHEDULER_PORT": "7000"})
    assert out["port"] == 7000
    # CLI overrides env; None CLI values are "not passed"
    out = layered_config("scheduler", defaults, str(cfg_file),
                         env={"BALLISTA_SCHEDULER_PORT": "7000"},
                         cli={"port": "8000", "namespace": None})
    assert out["port"] == 8000 and out["namespace"] == "filens"


def test_layered_config_bad_coercion(tmp_path):
    with pytest.raises(ValueError, match="port"):
        layered_config("scheduler", {"port": 1},
                       env={"BALLISTA_SCHEDULER_PORT": "not-a-number"})
