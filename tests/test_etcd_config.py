"""HA state backend (etcd v3 wire protocol) + layered config files.

(reference: rust/scheduler/src/state/etcd.rs:29-113 — get/prefix/
put-with-lease/distributed-lock; configure_me TOML layering in
scheduler/main.rs:65-66.) No etcd binary exists in this environment, so
the backend is exercised against an in-process fake speaking the same
wire protocol."""

import threading
import time

import pytest

from ballista_tpu.distributed.config import layered_config
from ballista_tpu.distributed.etcd import (
    EtcdBackend,
    FakeEtcdServer,
    prefix_range_end,
)
from ballista_tpu.distributed.state import SchedulerState
from ballista_tpu.distributed.types import ExecutorMeta


@pytest.fixture()
def etcd():
    server = FakeEtcdServer()
    backend = EtcdBackend(f"localhost:{server.port}")
    yield backend
    backend.close()
    server.stop()


def test_prefix_range_end():
    assert prefix_range_end(b"/a") == b"/b"
    assert prefix_range_end(b"/a\xff") == b"/b"
    assert prefix_range_end(b"\xff") == b"\0"


def test_etcd_kv_roundtrip(etcd):
    etcd.put("/ballista/ns/a", b"1")
    etcd.put("/ballista/ns/b", b"2")
    etcd.put("/other", b"3")
    assert etcd.get("/ballista/ns/a") == b"1"
    assert etcd.get("/missing") is None
    got = etcd.get_from_prefix("/ballista/ns/")
    assert got == [("/ballista/ns/a", b"1"), ("/ballista/ns/b", b"2")]
    etcd.delete("/ballista/ns/a")
    assert etcd.get("/ballista/ns/a") is None


def test_etcd_lease_expiry(etcd):
    etcd.put("/lease/k", b"v", lease_secs=1)
    assert etcd.get("/lease/k") == b"v"
    time.sleep(1.2)
    assert etcd.get("/lease/k") is None
    assert etcd.get_from_prefix("/lease/") == []


def test_etcd_distributed_lock_mutual_exclusion(etcd):
    order = []

    def worker(tag):
        with etcd.lock():
            order.append((tag, "in"))
            time.sleep(0.05)
            order.append((tag, "out"))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # critical sections never interleave
    for i in range(0, len(order), 2):
        assert order[i][0] == order[i + 1][0]
        assert order[i][1] == "in" and order[i + 1][1] == "out"


def test_scheduler_state_over_etcd(etcd):
    """The whole state machine runs against the etcd wire protocol."""
    state = SchedulerState(etcd, "ha")
    state.save_executor_metadata(ExecutorMeta("e1", "host1", 1234, 8))
    metas = state.get_executors_metadata()
    assert [m.id for m in metas] == ["e1"] and metas[0].num_devices == 8
    # a standby scheduler over the same etcd rehydrates the same state
    # (HA = failover; see etcd.py docstring for the active-active caveat)
    state2 = SchedulerState(etcd, "ha")
    assert [m.id for m in state2.get_executors_metadata()] == ["e1"]


# ---------------------------------------------------------------------------
# layered config
# ---------------------------------------------------------------------------


def test_layered_config_precedence(tmp_path):
    cfg_file = tmp_path / "scheduler.toml"
    cfg_file.write_text('port = 6000\nnamespace = "filens"\n')
    defaults = {"port": 50050, "namespace": "default", "bind_host": "0.0.0.0"}
    # file overrides defaults
    out = layered_config("scheduler", defaults, str(cfg_file), env={})
    assert out["port"] == 6000 and out["namespace"] == "filens"
    assert out["bind_host"] == "0.0.0.0"
    # env overrides file (with type coercion)
    out = layered_config("scheduler", defaults, str(cfg_file),
                         env={"BALLISTA_SCHEDULER_PORT": "7000"})
    assert out["port"] == 7000
    # CLI overrides env; None CLI values are "not passed"
    out = layered_config("scheduler", defaults, str(cfg_file),
                         env={"BALLISTA_SCHEDULER_PORT": "7000"},
                         cli={"port": "8000", "namespace": None})
    assert out["port"] == 8000 and out["namespace"] == "filens"


def test_layered_config_bad_coercion(tmp_path):
    with pytest.raises(ValueError, match="port"):
        layered_config("scheduler", {"port": 1},
                       env={"BALLISTA_SCHEDULER_PORT": "not-a-number"})
