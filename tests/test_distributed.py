"""Distributed runtime tests: state machine, direct service calls, and an
in-process cluster running real queries end-to-end.

Test style follows the reference (reference: rust/scheduler/src/lib.rs:
444-491 invokes poll_work directly with tonic::Request — no sockets; state
tests against temp sled at state/mod.rs:450-787) plus what it lacks: a real
multi-executor end-to-end query with shuffle."""

import time

import numpy as np
import pytest

from ballista_tpu import schema, col, lit, sum_, count, Int64, Decimal, Utf8
from ballista_tpu.distributed.executor import LocalCluster
from ballista_tpu.distributed.scheduler import SchedulerService
from ballista_tpu.distributed.state import (
    MemoryBackend,
    SchedulerState,
    SqliteBackend,
)
from ballista_tpu.distributed.types import (
    ExecutorMeta,
    JobStatus,
    PartitionId,
    TaskStatus,
)
from ballista_tpu.logical import LogicalPlanBuilder
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu import serde


# ---------------------------------------------------------------------------
# KV + state machine
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("backend_fn", [
    lambda tmp: MemoryBackend(),
    lambda tmp: SqliteBackend(str(tmp / "state.db")),
])
def test_kv_backend(tmp_path, backend_fn):
    kv = backend_fn(tmp_path)
    kv.put("/a/b", b"1")
    kv.put("/a/c", b"2")
    kv.put("/b/d", b"3")
    assert kv.get("/a/b") == b"1"
    assert kv.get("/missing") is None
    assert [k for k, _ in kv.get_from_prefix("/a")] == ["/a/b", "/a/c"]
    kv.delete("/a/b")
    assert kv.get("/a/b") is None


def test_executor_lease(tmp_path):
    st = SchedulerState(MemoryBackend())
    st.save_executor_metadata(ExecutorMeta("e1", "h", 1, 1))
    assert len(st.get_executors_metadata()) == 1


def test_job_status_machine():
    st = SchedulerState(MemoryBackend())
    st.save_job_status("j1", JobStatus("queued"))
    st.save_stage_plan("j1", 1, b"x", 2, [])
    st.save_stage_plan("j1", 2, b"y", 1, [1])
    for p in range(2):
        st.save_task_status(TaskStatus(PartitionId("j1", 1, p)))
    st.save_task_status(TaskStatus(PartitionId("j1", 2, 0)))
    st.enqueue_job("j1")

    # only stage 1 tasks are ready (stage 2 depends on stage 1)
    t1, t2 = st.next_task(), st.next_task()
    assert {t1.stage_id, t2.stage_id} == {1}
    assert st.next_task() is None

    st.save_executor_metadata(ExecutorMeta("e1", "h", 1))
    for t in (t1, t2):
        st.task_completed(
            TaskStatus(t, "completed", executor_id="e1", path="p", stats={})
        )
    # stage 1 complete -> stage 2 becomes ready
    t3 = st.next_task()
    assert t3 is not None and t3.stage_id == 2
    st.task_completed(
        TaskStatus(t3, "completed", executor_id="e1", path="p", stats={})
    )
    st.synchronize_job_status("j1")
    js = st.get_job_status("j1")
    assert js.state == "completed"
    assert len(js.partition_locations) == 1


def test_task_prefix_no_stage_collision():
    """Stage 1's task prefix must not match stages 10+ (regression)."""
    st = SchedulerState(MemoryBackend())
    for sid in (1, 10):
        st.save_stage_plan("j", sid, b"x", 1, [])
        st.save_task_status(TaskStatus(PartitionId("j", sid, 0)))
    st.save_task_status(
        TaskStatus(PartitionId("j", 10, 0), "completed", executor_id="e",
                   path="p", stats={})
    )
    s1 = st.get_task_statuses("j", 1)
    assert len(s1) == 1 and s1[0].state is None
    assert not st._stage_complete("j", 1)


def test_sqlite_state_rehydration(tmp_path):
    """A restarted scheduler must resume pending jobs from sqlite."""
    db = str(tmp_path / "st.db")
    st = SchedulerState(SqliteBackend(db))
    st.save_job_status("jr", JobStatus("queued"))
    st.save_stage_plan("jr", 1, b"x", 2, [])
    st.save_stage_plan("jr", 2, b"y", 1, [1])
    for p in range(2):
        st.save_task_status(TaskStatus(PartitionId("jr", 1, p)))
    st.save_task_status(TaskStatus(PartitionId("jr", 2, 0)))
    st.enqueue_job("jr")
    t = st.next_task()  # one task taken, scheduler "dies" now
    st.save_task_status(TaskStatus(t, "running", executor_id="e1"))

    st2 = SchedulerState(SqliteBackend(db))  # restart
    got = set()
    while (nt := st2.next_task()) is not None:
        got.add((nt.stage_id, nt.partition_id))
    # both stage-1 tasks are runnable again (the running one is requeued —
    # its executor's completion report died with the old scheduler)
    assert got == {(1, 0), (1, 1)}


def test_failed_task_fails_job():
    st = SchedulerState(MemoryBackend())
    st.save_job_status("j2", JobStatus("queued"))
    st.save_stage_plan("j2", 1, b"x", 1, [])
    st.save_task_status(TaskStatus(PartitionId("j2", 1, 0)))
    st.enqueue_job("j2")
    t = st.next_task()
    st.save_task_status(TaskStatus(t, "failed", error="boom"))
    st.synchronize_job_status("j2")
    js = st.get_job_status("j2")
    assert js.state == "failed" and "boom" in js.error


# ---------------------------------------------------------------------------
# Direct service calls (no sockets)
# ---------------------------------------------------------------------------


def _mem_table(tmp_path):
    p = tmp_path / "t.tbl"
    lines = [f"{i}|{(i % 7) + 0.25:.2f}|k{i % 3}|" for i in range(100)]
    p.write_text("\n".join(lines) + "\n")
    from ballista_tpu.io import TblSource

    s = schema(("a", Int64), ("b", Decimal(2)), ("c", Utf8))
    return TblSource(str(p), s)


def test_poll_work_direct(tmp_path):
    svc = SchedulerService(SchedulerState(MemoryBackend()))
    src = _mem_table(tmp_path)
    plan = (
        LogicalPlanBuilder.scan("t", src)
        .aggregate([col("c")], [sum_(col("b")).alias("s")])
        .build()
    )
    params = pb.ExecuteQueryParams()
    params.logical_plan.CopyFrom(serde.plan_to_proto(plan))
    job_id = svc.ExecuteQuery(params).job_id
    assert len(job_id) == 7

    # wait for background planning
    deadline = time.time() + 10
    while not svc.state.stage_ids(job_id):
        assert time.time() < deadline, "planning never finished"
        time.sleep(0.05)

    poll = pb.PollWorkParams(can_accept_task=True)
    poll.metadata.id = "e1"
    poll.metadata.host = "localhost"
    poll.metadata.port = 7777
    result = svc.PollWork(poll)
    assert result.HasField("task")
    assert result.task.task_id.job_id == job_id
    # executor now registered
    got = svc.GetExecutorsMetadata(pb.GetExecutorsMetadataParams())
    assert [e.id for e in got.metadata] == ["e1"]


# ---------------------------------------------------------------------------
# In-process cluster end-to-end
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(num_executors=2, concurrent_tasks=2)
    yield c
    c.shutdown()


def test_cluster_query_end_to_end(cluster, tmp_path):
    src = _mem_table(tmp_path)
    from ballista_tpu.client import BallistaContext

    ctx = BallistaContext.remote("localhost", cluster.port)
    ctx.register_source("t", src)
    df = ctx.sql(
        "select c, sum(b) as s, count(*) as n from t group by c order by c"
    )
    got = df.collect()

    import pandas as pd

    a = np.arange(100)
    exp = (
        pd.DataFrame({"c": [f"k{i % 3}" for i in a], "b": (a % 7) + 0.25})
        .groupby("c")
        .agg(s=("b", "sum"), n=("b", "size"))
        .reset_index()
        .sort_values("c")
    )
    np.testing.assert_array_equal(got["c"], exp["c"])
    np.testing.assert_allclose(got["s"], exp["s"], rtol=1e-9)
    np.testing.assert_array_equal(got["n"], exp["n"])


def test_cluster_job_timeout_setting(cluster, tmp_path):
    """job.timeout is honored on both remote collect paths: a zero
    timeout trips before completion, a generous one completes, and a
    malformed value fails fast (pre-submit) with a tagged error."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.errors import ClusterError

    src = _mem_table(tmp_path)
    ctx = BallistaContext.remote("localhost", cluster.port,
                                 **{"job.timeout": "0.0"})
    ctx.register_source("t", src)
    with pytest.raises(ClusterError, match="timed out"):
        ctx.sql("select count(*) as n from t").collect()

    ctx = BallistaContext.remote("localhost", cluster.port,
                                 **{"job.timeout": "not-a-number"})
    ctx.register_source("t", src)
    with pytest.raises(ClusterError, match="job.timeout"):
        ctx.sql("select count(*) as n from t").collect()

    ctx = BallistaContext.remote("localhost", cluster.port,
                                 **{"job.timeout": "120"})
    ctx.register_source("t", src)
    got = ctx.sql("select count(*) as n from t").collect()
    assert int(got["n"][0]) == 100


def test_cluster_join_query(cluster, tmp_path):
    from ballista_tpu.client import BallistaContext

    d = tmp_path / "dim.tbl"
    d.write_text("".join(f"{i}|cat{i % 2}|\n" for i in range(3)))
    f = tmp_path / "fact.tbl"
    f.write_text("".join(f"{i}|{i % 3}|{i + 0.5:.2f}|\n" for i in range(30)))
    from ballista_tpu.io import TblSource

    dim_s = schema(("dkey", Int64), ("cat", Utf8))
    fact_s = schema(("fid", Int64), ("fkey", Int64), ("v", Decimal(2)))
    ctx = BallistaContext.remote("localhost", cluster.port)
    ctx.register_source("dim", TblSource(str(d), dim_s), primary_key="dkey")
    ctx.register_source("fact", TblSource(str(f), fact_s))
    got = ctx.sql(
        "select cat, sum(v) as sv from fact, dim "
        "where fkey = dkey group by cat order by cat"
    ).collect()
    import pandas as pd

    a = np.arange(30)
    fact_df = pd.DataFrame({"fkey": a % 3, "v": a + 0.5})
    fact_df["cat"] = fact_df.fkey.map(lambda k: f"cat{k % 2}")
    exp = fact_df.groupby("cat").v.sum().reset_index().sort_values("cat")
    np.testing.assert_array_equal(got["cat"], exp["cat"])
    np.testing.assert_allclose(got["sv"], exp["v"], rtol=1e-9)


def test_cluster_shuffle_over_sockets_only(cluster, tmp_path, monkeypatch):
    """Force every shuffle fetch over the data-plane socket (the
    cross-host path): LocalCluster executors share a filesystem, so the
    local-path shortcut would otherwise hide the remote protocol."""
    from ballista_tpu.physical.shuffle import ShuffleReaderExec

    monkeypatch.setattr(ShuffleReaderExec, "FORCE_REMOTE", True)
    src = _mem_table(tmp_path)
    from ballista_tpu.client import BallistaContext

    ctx = BallistaContext.remote("localhost", cluster.port)
    ctx.register_source("t", src)
    got = ctx.sql(
        "select c, sum(b) as s from t group by c order by c"
    ).collect()
    import pandas as pd

    a = np.arange(100)
    exp = (
        pd.DataFrame({"c": [f"k{i % 3}" for i in a], "b": (a % 7) + 0.25})
        .groupby("c").agg(s=("b", "sum")).reset_index().sort_values("c")
    )
    np.testing.assert_array_equal(got["c"], exp["c"])
    np.testing.assert_allclose(got["s"], exp["s"], rtol=1e-9)


def test_cluster_hash_repartition_shuffle(cluster, tmp_path):
    """Distributed hash shuffle: a Repartition stage writes one shuffle-q
    file per consumer partition; consumers read the q-files of every
    producer. Results must match the unshuffled standalone run."""
    src = _mem_table(tmp_path)
    from ballista_tpu.client import BallistaContext

    ctx = BallistaContext.remote("localhost", cluster.port)
    ctx.register_source("t", src)
    df = (
        ctx.table("t")
        .repartition(3, [col("c")])
        .aggregate([col("c")], [sum_(col("b")).alias("s"),
                                count().alias("n")])
        .sort(col("c"))
    )
    got = df.collect()
    import pandas as pd

    a = np.arange(100)
    exp = (
        pd.DataFrame({"c": [f"k{i % 3}" for i in a], "b": (a % 7) + 0.25})
        .groupby("c").agg(s=("b", "sum"), n=("b", "size")).reset_index()
        .sort_values("c")
    )
    np.testing.assert_array_equal(got["c"], exp["c"])
    np.testing.assert_allclose(got["s"], exp["s"], rtol=1e-9)
    np.testing.assert_array_equal(got["n"], exp["n"])


def test_produce_diagram(tmp_path):
    from ballista_tpu.distributed.planner import DistributedPlanner
    from ballista_tpu.execution import plan_logical
    from ballista_tpu.logical import LogicalPlanBuilder
    from ballista_tpu.utils import produce_diagram
    from ballista_tpu import col, sum_

    src = _mem_table(tmp_path)
    plan = (
        LogicalPlanBuilder.scan("t", src)
        .aggregate([col("c")], [sum_(col("b")).alias("s")])
        .build()
    )
    stages = DistributedPlanner().plan_query_stages("j1", plan_logical(plan))
    dot = produce_diagram(stages)
    assert dot.startswith("digraph G {") and dot.endswith("}")
    assert "HashAggregateExec" in dot and "Stage" in dot
    # cross-stage dashed edge from producer into the shuffle reader
    assert "style=dashed" in dot


def test_cluster_task_failure_fails_job(cluster, tmp_path):
    """A task that errors at scan time must fail the job with the error
    surfaced to the client (reference: any failed task fails the job,
    state/mod.rs:342-346)."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.errors import ClusterError
    from ballista_tpu.io import TblSource
    from ballista_tpu import schema, Int64

    p = tmp_path / "bad.tbl"
    p.write_text("1|\nnot-a-number|\n")  # parse error at execution time
    src = TblSource(str(p), schema(("a", Int64)))
    ctx = BallistaContext.remote("localhost", cluster.port)
    ctx.register_source("bad", src)
    with pytest.raises(ClusterError, match="failed"):
        ctx.sql("select sum(a) as s from bad").collect()


def test_utf8_hash_partition_stable_across_dictionaries():
    """Equal strings must hash to the same partition regardless of which
    producer-local dictionary encoded them (regression: hashing codes)."""
    import jax.numpy as jnp

    from ballista_tpu.columnar import ColumnBatch, Dictionary
    from ballista_tpu.kernels.expr_eval import Evaluator
    from ballista_tpu.physical.operators import compute_partition_ids

    s = schema(("c", Utf8))
    d1, codes1 = Dictionary.encode(["apple", "banana"])   # banana -> 1
    d2, codes2 = Dictionary.encode(["banana", "cherry"])  # banana -> 0
    b1 = ColumnBatch.from_numpy(s, {"c": codes1}, {"c": d1}, capacity=8)
    b2 = ColumnBatch.from_numpy(s, {"c": codes2}, {"c": d2}, capacity=8)
    ev = Evaluator(s)
    p1 = np.asarray(compute_partition_ids(b1, [col("c")], 5, 0, ev))
    p2 = np.asarray(compute_partition_ids(b2, [col("c")], 5, 0, ev))
    # 'banana' is row 1 in b1 and row 0 in b2
    assert p1[1] == p2[0], "same string must land on the same partition"


def test_concat_batches_unifies_dictionaries():
    from ballista_tpu.columnar import ColumnBatch, Dictionary
    from ballista_tpu.physical.base import concat_batches

    s = schema(("c", Utf8))
    d1, codes1 = Dictionary.encode(["x", "y"])
    d2, codes2 = Dictionary.encode(["y", "z"])
    b1 = ColumnBatch.from_numpy(s, {"c": codes1}, {"c": d1}, capacity=4)
    b2 = ColumnBatch.from_numpy(s, {"c": codes2}, {"c": d2}, capacity=4)
    out = concat_batches(s, [b1, b2]).to_pydict()
    assert list(out["c"]) == ["x", "y", "y", "z"]
