"""EXPLAIN end-to-end: SQL text -> plan rows, both execution modes.

Reference surface: rust/core/proto/ballista.proto:232 ExplainNode (the
reference serializes DataFusion's SQL EXPLAIN); here EXPLAIN renders at
physical-planning time and the rows execute as a normal leaf operator, so
the distributed path needs no special result channel.
"""

import numpy as np
import pytest

from ballista_tpu import schema, Int64, Utf8
from ballista_tpu.client import BallistaContext
from ballista_tpu.io import TblSource
from ballista_tpu import serde
from ballista_tpu import logical as lp
from ballista_tpu.physical.explain import ExplainExec


def _make_ctx(tmp_path):
    p = tmp_path / "t.tbl"
    p.write_text("".join(f"{i}|k{i % 3}|\n" for i in range(50)))
    ctx = BallistaContext.standalone()
    ctx.register_source("t", TblSource(str(p), schema(("a", Int64),
                                                      ("c", Utf8))))
    return ctx


def test_explain_standalone(tmp_path):
    ctx = _make_ctx(tmp_path)
    out = ctx.sql(
        "EXPLAIN SELECT c, sum(a) FROM t WHERE a > 5 GROUP BY c"
    ).collect()
    assert list(out.columns) == ["plan_type", "plan"]
    types = out["plan_type"].tolist()
    assert types == ["logical_plan", "physical_plan"]
    logical_text = out["plan"][0]
    assert "Aggregate" in logical_text and "TableScan" in logical_text
    assert "HashAggregateExec" in out["plan"][1]


def test_explain_verbose_shows_preoptimization_plan(tmp_path):
    ctx = _make_ctx(tmp_path)
    out = ctx.sql("EXPLAIN VERBOSE SELECT a FROM t WHERE a > 5").collect()
    types = out["plan_type"].tolist()
    assert types[0] == "initial_logical_plan"
    assert "logical_plan" in types and "physical_plan" in types


def test_explain_schema_and_df_api(tmp_path):
    ctx = _make_ctx(tmp_path)
    df = ctx.sql("EXPLAIN SELECT a FROM t")
    names = list(df.schema().names())
    assert names == ["plan_type", "plan"]


def test_verbose_is_soft_keyword(tmp_path):
    """A column named ``verbose`` (or ``explain``) must keep working —
    the words are contextual keywords, special only at statement start."""
    p = tmp_path / "v.tbl"
    p.write_text("".join(f"{i}|{i * 2}|\n" for i in range(10)))
    ctx = BallistaContext.standalone()
    ctx.register_source("v", TblSource(str(p), schema(("verbose", Int64),
                                                      ("explain", Int64))))
    out = ctx.sql(
        "SELECT verbose, explain FROM v WHERE verbose > 3 ORDER BY verbose"
    ).collect()
    assert out["verbose"].tolist() == [4, 5, 6, 7, 8, 9]
    assert out["explain"].tolist() == [8, 10, 12, 14, 16, 18]


def test_explain_logical_serde_roundtrip(tmp_path):
    ctx = _make_ctx(tmp_path)
    df = ctx.sql("EXPLAIN VERBOSE SELECT a FROM t")
    plan = df.plan
    assert isinstance(plan, lp.Explain) and plan.verbose
    rt = serde.plan_from_proto(serde.plan_to_proto(plan))
    assert isinstance(rt, lp.Explain)
    assert rt.verbose is True
    assert list(rt.schema().names()) == ["plan_type", "plan"]
    assert rt.input.schema().names() == plan.input.schema().names()


def test_explain_physical_serde_roundtrip():
    node = ExplainExec([("logical_plan", "Scan: t\n"),
                        ("physical_plan", "ScanExec: t\n")])
    rt = serde.physical_from_proto(serde.physical_to_proto(node))
    assert isinstance(rt, ExplainExec)
    assert rt.rows == node.rows
    got = list(rt.execute(0))[0].to_pydict()
    assert got["plan_type"].tolist() == ["logical_plan", "physical_plan"]


def test_explain_through_cluster(tmp_path):
    """Server-planned EXPLAIN: SQL travels to the scheduler, the rendered
    rows come back over the standard distributed fetch path."""
    from ballista_tpu.distributed.executor import LocalCluster

    p = tmp_path / "t.tbl"
    p.write_text("".join(f"{i}|k{i % 3}|\n" for i in range(50)))
    src = TblSource(str(p), schema(("a", Int64), ("c", Utf8)))
    cluster = LocalCluster(num_executors=1, concurrent_tasks=1)
    try:
        ctx = BallistaContext.remote("localhost", cluster.port,
                                     **{"plan.server": "on"})
        ctx.register_source("t", src)
        out = ctx.sql("EXPLAIN SELECT c, sum(a) FROM t GROUP BY c").collect()
        assert out["plan_type"].tolist() == ["logical_plan", "physical_plan"]
        assert "Aggregate" in out["plan"][0]
    finally:
        cluster.shutdown()


def test_array_scalar_function(tmp_path):
    """ARRAY constructor (reference: rust/core/proto/ballista.proto:105):
    rectangular fixed-size-list column, collectable to per-row vectors."""
    p = tmp_path / "n.tbl"
    p.write_text("".join(f"{i}|{i * 10}|\n" for i in range(5)))
    ctx = BallistaContext.standalone()
    ctx.register_source("n", TblSource(str(p), schema(("x", Int64),
                                                      ("y", Int64))))
    out = ctx.sql("SELECT array(x, y) AS v FROM n").collect()
    assert len(out) == 5
    row0 = out["v"].iloc[0]
    np.testing.assert_array_equal(np.asarray(row0, dtype=np.int64), [0, 0])
    row3 = out["v"].iloc[3]
    np.testing.assert_array_equal(np.asarray(row3, dtype=np.int64), [3, 30])


def test_array_crosses_stage_boundary(tmp_path):
    """List column through an intermediate shuffle stage (ORDER BY forces
    a merge stage, so the array travels via IPC shuffle files and is
    rebuilt by batches_from_parts — the 2-D padding path)."""
    from ballista_tpu.distributed.executor import LocalCluster

    p = tmp_path / "n.tbl"
    p.write_text("".join(f"{i}|{i * 10}|\n" for i in range(16)))
    src = TblSource(str(p), schema(("x", Int64), ("y", Int64)))
    cluster = LocalCluster(num_executors=2, concurrent_tasks=2)
    try:
        ctx = BallistaContext.remote("localhost", cluster.port)
        ctx.register_source("n", src)
        out = ctx.sql(
            "SELECT x, array(x, y) AS v FROM n ORDER BY x DESC LIMIT 5"
        ).collect()
        assert out["x"].tolist() == [15, 14, 13, 12, 11]
        for i, xv in enumerate(out["x"].tolist()):
            np.testing.assert_array_equal(
                np.asarray(out["v"].iloc[i], dtype=np.int64), [xv, xv * 10])
    finally:
        cluster.shutdown()


def test_array_dtype_serde_roundtrip():
    from ballista_tpu.datatypes import FixedSizeList, Int64 as I64, Decimal

    for dt in (FixedSizeList(I64, 3), FixedSizeList(Decimal(2), 2)):
        rt = serde.dtype_from_proto(serde.dtype_to_proto(dt))
        assert rt == dt, (rt, dt)
        assert rt.element == dt.element and rt.length == dt.length


def test_array_through_cluster(tmp_path):
    """array() results cross the distributed result path: the fixed-size
    list column is written as a real Arrow FixedSizeListArray and
    reconstructed client-side."""
    from ballista_tpu.distributed.executor import LocalCluster

    p = tmp_path / "n.tbl"
    p.write_text("".join(f"{i}|{i * 10}|\n" for i in range(8)))
    src = TblSource(str(p), schema(("x", Int64), ("y", Int64)))
    cluster = LocalCluster(num_executors=1, concurrent_tasks=1)
    try:
        ctx = BallistaContext.remote("localhost", cluster.port)
        ctx.register_source("n", src)
        out = ctx.sql("SELECT x, array(x, y) AS v FROM n").collect()
        assert len(out) == 8
        out = out.sort_values("x").reset_index(drop=True)
        for i in range(8):
            np.testing.assert_array_equal(
                np.asarray(out["v"].iloc[i], dtype=np.int64), [i, i * 10])
    finally:
        cluster.shutdown()
