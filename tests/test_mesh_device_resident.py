"""Device-resident mesh input path (SURVEY §7 "device-memory partition
cache").

Round 2's mesh path executed fused-stage producers on host, concatenated
every column in numpy, and re-uploaded per fused stage. These tests pin
the round-3 replacement: producer output is laid out over the mesh with
device gathers only (scalar live-count syncs are the only host reads),
and a fused stage whose producer is itself mesh-fused consumes the
producer's stacked HBM output directly — no re-assembly, no host
round-trip, and still zero shuffle files.
"""

import os

import numpy as np
import pandas as pd
import pytest

import jax

from ballista_tpu import Decimal, Int64, Utf8, schema
from ballista_tpu.client import BallistaContext
from ballista_tpu.distributed.executor import LocalCluster
from ballista_tpu.io import TblSource
from ballista_tpu.physical import mesh_input


def _no_shuffle_files(cluster):
    files = []
    for e in cluster.executors:
        for root, _, fs in os.walk(e.config.work_dir):
            files += [f for f in fs if f.startswith("shuffle-")]
    return files == [], files


def test_assemble_over_mesh_unifies_dictionaries(eight_devices, tmp_path):
    """Producer partitions with DIFFERENT utf8 dictionaries are unified
    on device: the stacked batch shares one dictionary and decodes to
    exactly the host rows."""
    from ballista_tpu.io import MemTableSource
    from ballista_tpu.logical import LogicalPlanBuilder
    from ballista_tpu.parallel.mesh import make_mesh
    from ballista_tpu.physical.planner import (
        PlannerOptions, create_physical_plan,
    )

    from ballista_tpu.columnar import ColumnBatch

    s = schema(("k", Utf8), ("v", Int64))
    # two partitions built independently -> distinct dictionaries
    parts = [
        {"k": ["apple", "pear", "apple"], "v": [1, 2, 3]},
        {"k": ["kiwi", "pear", "zucchini", "kiwi"], "v": [4, 5, 6, 7]},
    ]
    src = MemTableSource(
        s, [[ColumnBatch.from_pydict(s, p)] for p in parts]
    )
    plan = LogicalPlanBuilder.scan("t", src).build()
    phys = create_physical_plan(plan, PlannerOptions())

    mesh = make_mesh(8)
    mesh_input.reset_stats()
    stacked, cap = mesh_input.stacked_input(phys, s, mesh)
    assert mesh_input.STATS["slot_assemblies"] == 1

    # one shared dictionary across every device slot
    kcol = stacked.columns[0]
    assert kcol.dictionary is not None
    got = []
    for q in range(8):
        codes = np.asarray(kcol.values[q])
        live = np.asarray(stacked.selection[q])
        got += [kcol.dictionary.values[c] for c in codes[live]]
    exp = [k for p in parts for k in p["k"]]
    assert sorted(got) == sorted(exp)

    vcol = stacked.columns[1]
    got_v = []
    for q in range(8):
        live = np.asarray(stacked.selection[q])
        got_v += list(np.asarray(vcol.values[q])[live])
    assert sorted(got_v) == list(range(1, 8))


def test_chained_fused_stages_stay_in_hbm(eight_devices, tmp_path):
    """q5 shape: partitioned join AND shuffled aggregation both fuse; the
    aggregation's producer contains the fused join, so its input must be
    the join's stacked HBM output (chained), never a host re-assembly —
    and the whole query writes zero shuffle files."""
    d = tmp_path / "dim"
    d.mkdir()
    (d / "p0.tbl").write_text(
        "".join(f"{i}|cat{i % 5}|\n" for i in range(17)))
    f = tmp_path / "fact"
    f.mkdir()
    for part in range(3):
        rows = [f"{i}|{i % 17}|{i + 0.25:.2f}|\n"
                for i in range(300) if i % 3 == part]
        (f / f"p{part}.tbl").write_text("".join(rows))

    dim_s = schema(("dkey", Int64), ("cat", Utf8))
    fact_s = schema(("fid", Int64), ("fkey", Int64), ("v", Decimal(2)))
    cluster = LocalCluster(num_executors=1, concurrent_tasks=2,
                           num_devices=8)
    try:
        mesh_input.reset_stats()
        ctx = BallistaContext.remote(
            "localhost", cluster.port,
            **{"join.partitioned.threshold": "1", "join.partitions": "8",
               "agg.partitions": "8", "mesh.devices": "8"},
        )
        ctx.register_source("dim", TblSource(str(d), dim_s),
                            primary_key="dkey")
        ctx.register_source("fact", TblSource(str(f), fact_s))
        got = ctx.sql(
            "select cat, sum(v) as sv, count(*) as n from fact, dim "
            "where fkey = dkey group by cat order by cat"
        ).collect()

        a = np.arange(300)
        fd = pd.DataFrame({"fkey": a % 17, "v": a + 0.25})
        fd["cat"] = fd.fkey.map(lambda k: f"cat{k % 5}")
        exp = fd.groupby("cat").agg(sv=("v", "sum"), n=("v", "size")) \
            .reset_index().sort_values("cat")
        np.testing.assert_array_equal(got["cat"], exp["cat"])
        np.testing.assert_allclose(got["sv"], exp["sv"], rtol=1e-9)
        np.testing.assert_array_equal(got["n"].astype(np.int64),
                                      exp["n"].astype(np.int64))

        # the fused agg consumed the fused join's stacked output in HBM
        assert mesh_input.STATS["chained_stages"] >= 1, mesh_input.STATS
        ok, files = _no_shuffle_files(cluster)
        assert ok, f"host shuffle files written: {files}"
    finally:
        cluster.shutdown()


def test_host_funnel_is_gone():
    """The round-2 numpy producer funnel must not exist: mesh execs have
    no code path that materializes producer columns with np.asarray."""
    from ballista_tpu.physical import mesh_agg

    assert not hasattr(mesh_agg, "_run_producer_over_mesh")
    assert not hasattr(mesh_agg, "_stack_device_batches")


def test_stacked_compaction_bounds_chain_capacity(eight_devices):
    """A sparse stacked batch (few live rows in a huge capacity) is
    compacted per device before feeding the next fused stage, bounding
    the all_to_all buffer blowup in fused chains."""
    from ballista_tpu.columnar import ColumnBatch
    from ballista_tpu.parallel.mesh import make_mesh

    s = schema(("v", Int64))
    mesh = make_mesh(8)
    slot_batches = []
    for q in range(8):
        b = ColumnBatch.from_numpy(
            s, {"v": np.arange(3, dtype=np.int64) + 10 * q}, capacity=1024
        )
        slot_batches.append(b)
    stacked = mesh_input.stack_to_mesh(slot_batches, mesh)
    out = mesh_input._maybe_compact_stacked(stacked, mesh)
    assert int(out.selection.shape[1]) == 8  # 1024 -> 8
    for q in range(8):
        live = np.asarray(out.selection[q])
        assert list(np.asarray(out.columns[0].values[q])[live]) == \
            [10 * q, 10 * q + 1, 10 * q + 2]
