"""Memory-governed streaming shuffle: governor accounting, spill pool,
chunked IPC, flow-controlled data plane, and the spill-on/spill-off
determinism sweep (docs/shuffle.md).

The reference materializes whole partitions in memory on both shuffle
ends; this engine streams bounded Arrow-IPC chunks through a per-process
memory budget with disk spill past the watermark. These tests pin the
invariants that make that safe: charges always drain back to zero,
spilled chunks replay byte-identically (and a truncated segment is
DETECTED, never silently decoded), a saturated budget degrades to
streaming-from-disk rather than blocking, cancellation lands at chunk
boundaries, and query results are byte-identical spill-on vs spill-off
on both execution paths.
"""

import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from ballista_tpu import Int64, Utf8, schema
from ballista_tpu.client import BallistaContext
from ballista_tpu.columnar import ColumnBatch
from ballista_tpu.distributed import dataplane, spill
from ballista_tpu.distributed.executor import LocalCluster
from ballista_tpu.errors import IoError, QueryCancelled
from ballista_tpu.io import ipc
from ballista_tpu.lifecycle import CancelToken, bind_token
from ballista_tpu.physical.shuffle import ShuffleReaderExec

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _mkbatch(n=5000):
    s = schema(("a", Int64), ("k", Utf8))
    return s, ColumnBatch.from_pydict(s, {
        "a": list(range(n)),
        "k": [f"v{i % 7}" for i in range(n)],
    })


# ---------------------------------------------------------------------------
# governor accounting units
# ---------------------------------------------------------------------------


def test_governor_charge_release_watermark(monkeypatch):
    monkeypatch.setenv("BALLISTA_SHUFFLE_MEM_BUDGET", "10000")
    monkeypatch.setenv("BALLISTA_SHUFFLE_SPILL_WATERMARK", "0.8")
    gov = spill.ShuffleMemoryGovernor()
    assert gov.try_charge(4000) and gov.try_charge(4000)
    assert gov.inflight_bytes == 8000
    # 8000 + 4000 crosses the 8000 watermark -> refused, not blocked
    assert not gov.try_charge(4000)
    assert gov.denials == 1
    assert gov.inflight_bytes == 8000  # refused charge did not land
    gov.release(4000)
    assert gov.try_charge(100)
    gov.release(4100)
    gov.release(999999)  # over-release clamps at zero, never negative
    assert gov.inflight_bytes == 0
    assert gov.peak_inflight_bytes == 8000
    gov.note_spill(1234)
    st = gov.stats()
    assert st["spilled_bytes_total"] == 1234
    assert st["spill_chunks_total"] == 1


def test_governor_budget_is_dynamic(monkeypatch):
    """Knob reads happen per charge: tests/bench re-point the budget
    without process restarts or governor resets."""
    gov = spill.ShuffleMemoryGovernor()
    monkeypatch.setenv("BALLISTA_SHUFFLE_MEM_BUDGET", "8192")
    assert not gov.try_charge(8000)
    monkeypatch.setenv("BALLISTA_SHUFFLE_MEM_BUDGET", str(1 << 20))
    assert gov.try_charge(8000)
    gov.release(8000)


# ---------------------------------------------------------------------------
# spill pool: rotation, refcounted cleanup, truncation detection
# ---------------------------------------------------------------------------


def test_spill_pool_rotates_and_unlinks(tmp_path):
    pool = spill.SpillPool(str(tmp_path), max_file_bytes=1000)
    refs = [pool.append(bytes([i]) * 600) for i in range(4)]
    # 600B chunks against a 1000B rotation bound: segments roll over
    assert pool.segments_created >= 2
    for i, r in enumerate(refs):
        assert r.read() == bytes([i]) * 600
    live = {r._seg.path for r in refs}
    for r in refs:
        r.release()
    pool.close()
    for path in live:
        assert not os.path.exists(path), f"segment survived release: {path}"


def test_truncated_spill_segment_detected(tmp_path):
    pool = spill.SpillPool(str(tmp_path), max_file_bytes=1 << 20)
    ref = pool.append(b"x" * 500)
    with open(ref._seg.path, "r+b") as fh:
        fh.truncate(100)
    with pytest.raises(IoError):  # SpillCorrupt is IoError-shaped
        ref.read()
    ref.release()
    pool.close()


def test_torn_write_mid_segment_detected(tmp_path, monkeypatch):
    """A torn write that is NOT the last chunk of its segment must
    still be detected: later appends land at the file's REAL end, so
    the torn chunk's window would otherwise read back the neighbor's
    bytes with no short read at all."""
    from ballista_tpu.testing.faults import reload_faults

    monkeypatch.setenv("BALLISTA_FAULTS", "shuffle.spill.write=drop-once")
    reload_faults()
    try:
        pool = spill.SpillPool(str(tmp_path), max_file_bytes=1 << 20)
        torn = pool.append(b"A" * 1000)   # drop-once: 500 bytes on disk
        after = pool.append(b"B" * 1000)  # appends at the real end
        with pytest.raises(IoError, match="torn"):
            torn.read()
        assert after.read() == b"B" * 1000
        torn.release()
        after.release()
        pool.close()
    finally:
        monkeypatch.delenv("BALLISTA_FAULTS")
        reload_faults()


def test_chunk_buffer_spills_and_replays_in_order(tmp_path, monkeypatch):
    monkeypatch.setenv("BALLISTA_SHUFFLE_MEM_BUDGET", "8192")
    monkeypatch.setenv("BALLISTA_SHUFFLE_SPILL_DIR", str(tmp_path))
    spill._reset_pool()
    gov = spill.governor()
    base = gov.stats()["spilled_bytes_total"]
    buf = spill.ChunkBuffer()
    chunks = [bytes([i]) * 3000 for i in range(8)]  # 24 KB >> budget
    for c in chunks:
        buf.put(c)
    assert buf.spilled_bytes > 0, "tiny budget must divert to disk"
    assert gov.stats()["spilled_bytes_total"] > base
    assert b"".join(buf.chunks()) == b"".join(chunks)
    buf.close()
    # every charge drained: saturating then consuming leaks no budget
    assert gov.inflight_bytes == 0
    spill._reset_pool()


def test_chunk_buffer_close_releases_unconsumed(monkeypatch, tmp_path):
    monkeypatch.setenv("BALLISTA_SHUFFLE_MEM_BUDGET", str(1 << 20))
    monkeypatch.setenv("BALLISTA_SHUFFLE_SPILL_DIR", str(tmp_path))
    spill._reset_pool()
    gov = spill.governor()
    before = gov.inflight_bytes
    buf = spill.ChunkBuffer()
    for _ in range(4):
        buf.put(b"y" * 2000)
    assert gov.inflight_bytes > before
    buf.close()  # error path: nothing consumed
    assert gov.inflight_bytes == before
    spill._reset_pool()


# ---------------------------------------------------------------------------
# chunked IPC writer / incremental reader
# ---------------------------------------------------------------------------


def test_partition_writer_bounds_record_batches(tmp_path):
    s, b = _mkbatch()
    whole = str(tmp_path / "whole" / "data.arrow")
    sliced = str(tmp_path / "sliced" / "data.arrow")
    ipc.write_partition(whole, [b])
    w = ipc.PartitionWriter(sliced, chunk_bytes=4096)
    w.write_batch(b)
    st = w.close()
    assert st["num_batches"] > 4, "4 KiB bound must split the batch"
    n1, a1, _, d1, _ = ipc.read_partition_arrays(whole)
    n2, a2, _, d2, _ = ipc.read_partition_arrays(sliced)
    for name in n1:
        assert np.array_equal(a1[name], a2[name]), name


def test_reader_sniffs_legacy_file_format(tmp_path):
    """Pre-PR files (random-access FILE format) stay readable — the
    reader dispatches on the ARROW1 magic."""
    import pyarrow as pa

    s, b = _mkbatch(100)
    rb = ipc.batch_to_arrow(b)
    path = str(tmp_path / "legacy.arrow")
    with pa.OSFile(path, "wb") as sink:
        with pa.ipc.new_file(sink, rb.schema) as writer:
            writer.write_batch(rb)
    names, arrays, _, dicts, kinds = ipc.read_partition_arrays(path)
    assert list(arrays["a"]) == list(range(100))
    assert kinds["a"] == ("int64", 0)
    # and the stream-format path through a buffer works too
    stream_path = str(tmp_path / "s" / "data.arrow")
    ipc.write_partition(stream_path, [b])
    buf = open(stream_path, "rb").read()
    names2, arrays2, _, _, _ = ipc.read_partition_arrays(buf)
    assert np.array_equal(arrays2["a"], arrays["a"])


def test_incremental_decode_checks_cancel(tmp_path):
    """Chunk-level cancellation: a token fired mid-decode aborts at the
    next record-batch boundary instead of finishing the partition."""
    s, b = _mkbatch()
    path = str(tmp_path / "p" / "data.arrow")
    w = ipc.PartitionWriter(path, chunk_bytes=2048)
    w.write_batch(b)
    w.close()
    token = CancelToken()
    raw = open(path, "rb").read()

    def chunks():
        yield raw[:3000]
        token.cancel("test")
        yield raw[3000:]

    with bind_token(token):
        with pytest.raises(QueryCancelled):
            ipc.read_partition_arrays_from_chunks(chunks())


# ---------------------------------------------------------------------------
# flow-controlled data plane
# ---------------------------------------------------------------------------


@pytest.fixture
def plane(tmp_path):
    s, b = _mkbatch()
    wd = str(tmp_path / "wd")
    path = dataplane.partition_path(wd, "jobs1", 1, 0)
    ipc.write_partition(path, [b])
    server = dataplane.DataPlaneServer("localhost", 0, wd)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        yield server, path, s
    finally:
        server.close()


def test_stream_fetch_flow_control(plane):
    server, path, _ = plane
    raw = open(path, "rb").read()
    # window smaller than the payload: the server must suspend on acks
    chunks = list(dataplane.fetch_partition_chunks(
        "localhost", server.port, "jobs1", 1, 0,
        window_bytes=8192, chunk_bytes=4096))
    assert len(chunks) > 4
    assert b"".join(chunks) == raw


def test_stream_fetch_legacy_framing(plane):
    """A server without the streaming extension (the native C++ daemon
    path) answers whole-payload; the client still consumes in bounded
    chunks."""
    server, path, _ = plane
    raw = open(path, "rb").read()
    server.stream_serve = False
    try:
        chunks = list(dataplane.fetch_partition_chunks(
            "localhost", server.port, "jobs1", 1, 0, chunk_bytes=4096))
    finally:
        server.stream_serve = True
    assert len(chunks) > 4
    assert b"".join(chunks) == raw


def test_stream_abort_on_cancelled_job(plane):
    # a DISTINCT job id: the cancelled-job registry is process-global
    # by design (ids are unique in production), so poisoning the shared
    # fixture id would cancel every later test's streams too
    server, path, _ = plane
    import shutil

    dead = dataplane.partition_path(server.work_dir, "jobdead", 1, 0)
    os.makedirs(os.path.dirname(dead), exist_ok=True)
    shutil.copyfile(path, dead)
    dataplane.mark_job_cancelled("jobdead")
    with pytest.raises(IoError, match="cancelled"):
        list(dataplane.fetch_partition_chunks(
            "localhost", server.port, "jobdead", 1, 0, chunk_bytes=1024))


def test_stream_fetch_decode_matches_whole_fetch(plane):
    server, path, s = plane
    whole = dataplane.fetch_partition_bytes(
        "localhost", server.port, "jobs1", 1, 0)
    chunks = dataplane.fetch_partition_chunks(
        "localhost", server.port, "jobs1", 1, 0, chunk_bytes=4096)
    n1, a1, _, d1, _ = ipc.read_partition_arrays(whole)
    n2, a2, _, d2, _ = ipc.read_partition_arrays_from_chunks(chunks)
    for name in n1:
        assert np.array_equal(a1[name], a2[name]), name


def test_chunk_cancel_aborts_inflight_transfer(plane, monkeypatch):
    """The reader loop checks the cancel token at every chunk boundary:
    a token fired mid-transfer stops the fetch within one chunk instead
    of draining the stream."""
    server, path, _ = plane
    token = CancelToken()
    got = []
    with bind_token(token):
        from ballista_tpu.lifecycle import check_cancel

        with pytest.raises(QueryCancelled):
            for chunk in dataplane.fetch_partition_chunks(
                    "localhost", server.port, "jobs1", 1, 0,
                    chunk_bytes=1024, window_bytes=2048):
                check_cancel()
                got.append(chunk)
                if len(got) == 2:
                    token.cancel("test")
    raw_len = os.path.getsize(path)
    assert sum(len(c) for c in got) < raw_len, "fetch ran to completion"


# ---------------------------------------------------------------------------
# e2e: spill-forced vs spill-free determinism on both paths
# ---------------------------------------------------------------------------


def _tpch_ctx_standalone(data_dir):
    import sys

    sys.path.insert(0, REPO)
    from benchmarks.tpch.schema_def import register_tpch

    ctx = BallistaContext.standalone()
    register_tpch(ctx, data_dir, "tbl")
    return ctx


@pytest.fixture(scope="module")
def tpch_dir(tmp_path_factory):
    import sys

    sys.path.insert(0, REPO)
    from benchmarks.tpch import datagen

    d = str(tmp_path_factory.mktemp("tpch_spill"))
    datagen.generate(d, scale=0.01, num_parts=2)
    return d


def _assert_frames_identical(got: pd.DataFrame, exp: pd.DataFrame):
    assert list(got.columns) == list(exp.columns)
    assert len(got) == len(exp)
    for name in exp.columns:
        assert np.array_equal(got[name].to_numpy(), exp[name].to_numpy()), \
            f"column {name} differs"


@pytest.mark.parametrize("qname", ["q5", "q16"])
def test_spill_on_off_byte_identical(tpch_dir, tmp_path, monkeypatch,
                                     qname):
    """The acceptance gate: a spill-FORCED cluster run (tiny budget,
    every fetched chunk streamed from disk) produces byte-identical
    results to the spill-free run, and the standalone path under the
    same knobs matches both."""
    monkeypatch.setattr(ShuffleReaderExec, "FORCE_REMOTE", True)
    monkeypatch.setenv("BALLISTA_SHUFFLE_SPILL_DIR", str(tmp_path / "sp"))
    spill._reset_pool()
    sql = open(os.path.join(REPO, "benchmarks", "tpch", "queries",
                            f"{qname}.sql")).read()
    gov = spill.governor()

    def cluster_run():
        import sys

        sys.path.insert(0, REPO)
        from benchmarks.tpch.schema_def import register_tpch

        cluster = LocalCluster(num_executors=2, concurrent_tasks=2)
        try:
            ctx = BallistaContext("remote", "localhost", cluster.port,
                                  settings={"job.timeout": "120"})
            register_tpch(ctx, tpch_dir, "tbl")
            return ctx.sql(sql).collect()
        finally:
            cluster.shutdown()

    # spill-free: budget far above the workload
    monkeypatch.setenv("BALLISTA_SHUFFLE_MEM_BUDGET", str(1 << 30))
    free = cluster_run()
    spilled0 = gov.stats()["spilled_bytes_total"]

    # spill-forced: floor budget + tiny chunks -> disk lane engaged
    monkeypatch.setenv("BALLISTA_SHUFFLE_MEM_BUDGET", "4096")
    monkeypatch.setenv("BALLISTA_SHUFFLE_CHUNK_BYTES", "2048")
    forced = cluster_run()
    assert gov.stats()["spilled_bytes_total"] > spilled0, \
        "tiny budget did not engage the spill lane"
    assert gov.inflight_bytes == 0, "spill run leaked governed budget"
    _assert_frames_identical(forced, free)

    # standalone path under the same (tiny) knobs matches the cluster
    alone = _tpch_ctx_standalone(tpch_dir).sql(sql).collect()
    _assert_frames_identical(alone, free)
    spill._reset_pool()


def test_executors_table_carries_spill_columns():
    ctx = BallistaContext.standalone()
    rows = ctx.table("system.executors").collect()
    assert "shuffle_inflight_bytes" in rows.columns
    assert "spill_bytes_total" in rows.columns
    assert int(rows["shuffle_inflight_bytes"].iloc[0]) >= 0


# ---------------------------------------------------------------------------
# overhead gate: knobs armed must not move warm q1 (drift-cancelling)
# ---------------------------------------------------------------------------


def test_spill_overhead_q1_under_5pct(tmp_path_factory, monkeypatch):
    """Same drift-cancelling scheme as the other planes' gates: warm q1
    with the spill knobs ARMED (budget/watermark/chunk set) vs unset.
    The standalone hot path must not touch the governor at all, so any
    measurable delta is a coupling regression."""
    import sys

    sys.path.insert(0, REPO)
    from benchmarks.tpch import datagen
    from benchmarks.tpch.schema_def import register_tpch

    data_dir = str(tmp_path_factory.mktemp("tpch_spill_ovh"))
    datagen.generate(data_dir, scale=0.01, num_parts=1)
    ctx = BallistaContext.standalone()
    register_tpch(ctx, data_dir, "tbl")
    qdir = os.path.join(REPO, "benchmarks", "tpch", "queries")
    df = ctx.sql(open(os.path.join(qdir, "q1.sql")).read())
    df.collect()  # warm: jit compile + table caches

    def arm(on: bool):
        for k, v in (("BALLISTA_SHUFFLE_MEM_BUDGET", str(64 << 20)),
                     ("BALLISTA_SHUFFLE_CHUNK_BYTES", str(1 << 20)),
                     ("BALLISTA_SHUFFLE_SPILL_WATERMARK", "0.5")):
            if on:
                monkeypatch.setenv(k, v)
            else:
                monkeypatch.delenv(k, raising=False)

    def sample(on: bool) -> float:
        arm(on)
        t0 = time.perf_counter()
        for _ in range(3):
            df.collect()
        return time.perf_counter() - t0

    sample(True)
    sample(False)  # settle both paths before measuring

    def measure():
        offs, ons = [], []
        for i in range(9):
            if i % 2 == 0:
                offs.append(sample(False))
                ons.append(sample(True))
            else:
                ons.append(sample(True))
                offs.append(sample(False))
        return sorted(offs)[4], sorted(ons)[4]

    for _ in range(3):
        t_off, t_on = measure()
        if t_on <= t_off * 1.05 + 2e-3:
            return
    overhead = (t_on - t_off) / t_off
    raise AssertionError(
        f"spill-knob overhead {overhead:.1%} over the 5% gate "
        f"(off={t_off:.4f}s on={t_on:.4f}s)")
