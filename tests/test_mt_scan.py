"""Multithreaded native scanner: N workers parse disjoint byte sub-ranges
and merge (utf8 codes remapped onto a union dictionary), so results must
be byte-identical to the single-threaded parse. Reference role: DataFusion
reads partitions concurrently on tokio workers; here one big file fans out
across threads inside the C++ scanner itself.
"""

import os

import numpy as np
import pytest

from ballista_tpu.io import native
from ballista_tpu import schema, Int64, Utf8, Decimal, Date32


pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native scanner not built")


@pytest.fixture(autouse=True)
def tiny_thread_floor(monkeypatch):
    # let small test files still split across threads
    monkeypatch.setenv("TBLSCAN_MIN_THREAD_BYTES", "64")


def _write(tmp_path, rows=5000):
    p = tmp_path / "t.tbl"
    lines = []
    for i in range(rows):
        d = f"1995-{(i % 12) + 1:02d}-{(i % 28) + 1:02d}"
        val = "" if i % 17 == 0 else str(i)  # NULLs cross span boundaries
        lines.append(f"{val}|key{i % 41}|{i}.{i % 100:02d}|{d}|\n")
    p.write_text("".join(lines))
    return str(p)


SCHEMA = schema(("a", Int64), ("c", Utf8), ("d", Decimal(2)),
                ("dt", Date32))


def test_mt_equals_single_thread(tmp_path):
    path = _write(tmp_path)
    cols = ["a", "c", "d", "dt"]
    n1, a1, d1, v1 = native.scan_file(path, SCHEMA, cols, threads=1)
    n4, a4, d4, v4 = native.scan_file(path, SCHEMA, cols, threads=4)
    assert n1 == n4 == 5000
    for k in a1:
        np.testing.assert_array_equal(a1[k], a4[k], err_msg=k)
    np.testing.assert_array_equal(d1["c"], d4["c"])
    assert set(v1) == set(v4) == {"a"}
    np.testing.assert_array_equal(v1["a"], v4["a"])
    # decoded strings identical row-wise
    assert list(d1["c"][a1["c"]]) == list(d4["c"][a4["c"]])


def test_mt_composes_with_ranges(tmp_path):
    path = _write(tmp_path)
    size = os.path.getsize(path)
    nA, aA, _, _ = native.scan_file(path, SCHEMA, ["a"], offset=0,
                                    max_bytes=size // 2, threads=3)
    nB, aB, _, _ = native.scan_file(path, SCHEMA, ["a"],
                                    offset=size // 2, threads=3)
    assert nA + nB == 5000
    merged = np.concatenate([aA["a"], aB["a"]])
    # NULL rows parse as 0 in the physical array
    exp = np.array([0 if i % 17 == 0 else i for i in range(5000)])
    np.testing.assert_array_equal(merged, exp)


def test_mt_through_engine_query(tmp_path, monkeypatch):
    """Whole pipeline on a forced-multithreaded scan matches the oracle."""
    monkeypatch.setenv("BALLISTA_SCAN_THREADS", "4")
    path = _write(tmp_path)
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.io import TblSource

    ctx = BallistaContext.standalone()
    ctx.register_source("t", TblSource(path, SCHEMA))
    out = ctx.sql(
        "SELECT c, count(*) AS n, count(a) AS na FROM t GROUP BY c"
    ).collect()
    assert int(out["n"].sum()) == 5000
    # every 17th row has NULL a
    assert int(out["na"].sum()) == 5000 - len(range(0, 5000, 17))
