"""FULL OUTER JOIN (the reference leaves DataFrame joins TODO entirely,
rust/client/src/context.rs:287-290; our parser previously raised)."""

import numpy as np
import pandas as pd

from ballista_tpu import schema, Int64, Utf8
from ballista_tpu.client import BallistaContext
from ballista_tpu.io import MemTableSource


def _ctx(tables):
    ctx = BallistaContext.standalone()
    for name, (s, data, parts) in tables.items():
        ctx.register_source(
            name, MemTableSource.from_pydict(s, data, num_partitions=parts)
        )
    return ctx


def _check(got, exp, cols):
    got = got.sort_values(cols).reset_index(drop=True)
    exp = exp.sort_values(cols).reset_index(drop=True)
    assert len(got) == len(exp), (len(got), len(exp))
    for c in cols:
        g = got[c].astype(float).to_numpy()
        e = exp[c].astype(float).to_numpy()
        np.testing.assert_array_equal(np.isnan(g), np.isnan(e), err_msg=c)
        np.testing.assert_array_equal(g[~np.isnan(g)], e[~np.isnan(e)],
                                      err_msg=c)


def test_full_outer_join_basic():
    left = {"k": np.array([1, 2, 3, 4]), "v": np.array([10, 20, 30, 40])}
    right = {"j": np.array([3, 4, 5]), "w": np.array([300, 400, 500])}
    ls = schema(("k", Int64), ("v", Int64))
    rs = schema(("j", Int64), ("w", Int64))
    ctx = _ctx({"l": (ls, left, 2), "r": (rs, right, 1)})
    got = ctx.sql(
        "select v, w from l full outer join r on k = j"
    ).collect()
    exp = pd.DataFrame(left).merge(pd.DataFrame(right), how="outer",
                                   left_on="k", right_on="j")[["v", "w"]]
    _check(got, exp, ["v", "w"])


def test_full_outer_join_duplicates_and_multi_partition():
    rng = np.random.default_rng(11)
    left = {"k": rng.integers(0, 6, 40), "v": np.arange(40)}
    right = {"j": rng.integers(3, 10, 25), "w": np.arange(100, 125)}
    ls = schema(("k", Int64), ("v", Int64))
    rs = schema(("j", Int64), ("w", Int64))
    ctx = _ctx({"l": (ls, left, 3), "r": (rs, right, 2)})
    got = ctx.sql("select v, w from l full join r on k = j").collect()
    exp = pd.DataFrame(left).merge(pd.DataFrame(right), how="outer",
                                   left_on="k", right_on="j")[["v", "w"]]
    _check(got, exp, ["v", "w"])


def test_full_outer_preserves_null_key_build_rows():
    """A build row with a NULL join key matches nothing but must still
    appear in the full outer result with null probe columns."""
    import jax.numpy as jnp

    from ballista_tpu.columnar import Column, ColumnBatch
    from ballista_tpu.physical.join import JoinExec
    from ballista_tpu.physical.operators import ScanExec

    rs = schema(("j", Int64), ("w", Int64))
    cap = 8
    jvals = np.zeros(cap, np.int64)
    jvals[:3] = [2, 0, 5]  # row 1's key is NULL (validity False)
    wvals = np.zeros(cap, np.int64)
    wvals[:3] = [200, 999, 500]
    validity = np.zeros(cap, bool)
    validity[:3] = [True, False, True]
    sel = np.zeros(cap, bool)
    sel[:3] = True
    build_batch = ColumnBatch(
        rs,
        [Column(jnp.asarray(jvals), Int64, jnp.asarray(validity), None),
         Column(jnp.asarray(wvals), Int64, None, None)],
        jnp.asarray(sel), jnp.asarray(np.int32(3)),
    )
    build_src = MemTableSource(rs, [[build_batch]])

    ls = schema(("k", Int64), ("v", Int64))
    probe_src = MemTableSource.from_pydict(
        ls, {"k": np.array([1, 2]), "v": np.array([10, 20])},
        num_partitions=1,
    )
    j = JoinExec(ScanExec("r", build_src), ScanExec("l", probe_src),
                 on=[("j", "k")], how="full")
    rows = []
    for b in j.execute(0):
        d = b.to_pydict()
        rows += list(zip(d["v"].tolist(), d["w"].tolist()))
    # (10,NULL) unmatched probe, (20,200) matched, (NULL,999) NULL-key
    # build row, (NULL,500) unmatched build row
    assert len(rows) == 4, rows
    ws = sorted(w for _, w in rows if not (isinstance(w, float) and np.isnan(w)))
    assert ws == [200, 500, 999], rows


def test_full_outer_join_utf8_key():
    left = {"name": ["a", "b", "c"], "v": np.arange(3)}
    right = {"label": ["b", "c", "d"], "w": np.array([1, 2, 3])}
    ls = schema(("name", Utf8), ("v", Int64))
    rs = schema(("label", Utf8), ("w", Int64))
    ctx = _ctx({"l": (ls, left, 1), "r": (rs, right, 1)})
    got = ctx.sql(
        "select v, w from l full outer join r on name = label"
    ).collect()
    exp = pd.DataFrame(left).merge(pd.DataFrame(right), how="outer",
                                   left_on="name", right_on="label")[["v", "w"]]
    _check(got, exp, ["v", "w"])
