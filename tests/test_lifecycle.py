"""Query lifecycle control plane: cooperative cancellation, server-side
deadlines, graceful drain — proven by deterministic fault injection.

The reference cannot STOP work at all: no CancelJob RPC, a client
timeout only stops waiting, and killing an executor abandons tasks
mid-flight (SURVEY.md:336-343 "no task retry, no recovery, no fault
injection"). These tests pin the whole lifecycle: cancel mid-stage
frees slots and leaves the cluster reusable, server-side deadlines and
the slow-query killer reap runaway jobs, a draining executor never
loses completion reports, and the standalone path cancels at batch
boundaries. The chaos sweep at the bottom drives every recovery
behavior through the NAMED fault points in testing/faults.py — the
deterministic replacement for hand-crafted failure setups.

Style: service-level tests use direct calls + manually pumped
executors like test_recovery.py; e2e gates run a real LocalCluster.
"""

import logging
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pandas as pd
import pytest

from ballista_tpu import Int64, Utf8, col, schema, serde, sum_
from ballista_tpu.client import BallistaContext
from ballista_tpu.distributed.executor import (
    Executor,
    ExecutorConfig,
    LocalCluster,
)
from ballista_tpu.distributed.scheduler import (
    SchedulerService,
    serve_scheduler,
)
from ballista_tpu.distributed.state import MemoryBackend, SchedulerState
from ballista_tpu.distributed.types import JobStatus, PartitionId
from ballista_tpu.errors import (
    ClusterError,
    FaultInjected,
    QueryCancelled,
)
from ballista_tpu.io.memory import MemTableSource
from ballista_tpu.logical import LogicalPlanBuilder
from ballista_tpu.physical.shuffle import ShuffleReaderExec
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.testing.faults import (
    FaultConfigError,
    fault_point,
    parse_spec,
    reload_faults,
)
from ballista_tpu.testing import faults as faults_mod

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


@pytest.fixture
def faults_env():
    """Arm BALLISTA_FAULTS for the test; disarm + restore afterwards."""
    saved = os.environ.get("BALLISTA_FAULTS")

    def arm(spec: str):
        if spec:
            os.environ["BALLISTA_FAULTS"] = spec
        else:
            os.environ.pop("BALLISTA_FAULTS", None)
        reload_faults()

    yield arm
    if saved is None:
        os.environ.pop("BALLISTA_FAULTS", None)
    else:
        os.environ["BALLISTA_FAULTS"] = saved
    reload_faults()


def _wait_until(cond, timeout: float, msg: str):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(msg)


TSCHEMA = schema(("a", Int64), ("c", Utf8))
GROUPBY_SQL = "select c, sum(a) as s from t group by c order by c"
N_ROWS = 120


def _write_tbl(tmp_path, rows: int = N_ROWS, parts: int = 2) -> str:
    d = tmp_path / "t"
    d.mkdir()
    for part in range(parts):
        lines = [f"{i}|k{i % 7}|" for i in range(rows) if i % parts == part]
        (d / f"part{part}.tbl").write_text("\n".join(lines) + "\n")
    return str(d)


def _expected(rows: int = N_ROWS) -> pd.DataFrame:
    df = pd.DataFrame({"a": range(rows),
                       "c": [f"k{i % 7}" for i in range(rows)]})
    out = (df.groupby("c", as_index=False)["a"].sum()
           .rename(columns={"a": "s"})
           .sort_values("c").reset_index(drop=True))
    return out


def _assert_identical(got: pd.DataFrame, exp: pd.DataFrame):
    """Byte-identical: exact values, no float tolerance."""
    assert list(got.columns) == list(exp.columns)
    assert len(got) == len(exp)
    for name in exp.columns:
        g, e = got[name].to_numpy(), exp[name].to_numpy()
        assert np.array_equal(g, e), f"column {name}: {g} != {e}"


def _remote_ctx(cluster, **extra) -> BallistaContext:
    settings = {"job.timeout": "60"}
    settings.update(extra)
    return BallistaContext("remote", "localhost", cluster.port,
                          settings=settings)


def _source(tmp_path):
    """Two partition files -> a 2-task producer stage (recovery-test
    idiom)."""
    from ballista_tpu.io import TblSource

    return TblSource(_write_tbl(tmp_path), TSCHEMA)


def _submit_groupby(svc, src, deadline_secs: float = 0.0) -> str:
    plan = (
        LogicalPlanBuilder.scan("t", src)
        .aggregate([col("c")], [sum_(col("a")).alias("s")])
        .build()
    )
    params = pb.ExecuteQueryParams()
    params.logical_plan.CopyFrom(serde.plan_to_proto(plan))
    if deadline_secs:
        params.deadline_secs = deadline_secs
    job_id = svc.ExecuteQuery(params).job_id
    deadline = time.time() + 10
    while not svc.state.stage_ids(job_id):
        assert time.time() < deadline, "planning never finished"
        time.sleep(0.05)
    while not svc.state._ready:
        assert time.time() < deadline, "job never enqueued"
        time.sleep(0.05)
    return job_id


def _pump(svc, executor, run=True):
    """One manual poll cycle (recovery-test idiom). Returns the
    PollWorkResult so callers can inspect cancelled_jobs."""
    params = pb.PollWorkParams(can_accept_task=run)
    params.metadata.id = executor.id
    params.metadata.host = executor.config.host
    params.metadata.port = executor.port
    params.metadata.num_devices = 1
    with executor._status_lock:
        for st in executor._pending_status:
            params.task_status.append(st)
        executor._pending_status.clear()
    result = svc.PollWork(params)
    if run and result.HasField("task"):
        td = result.task
        pid = PartitionId(td.task_id.job_id, td.task_id.stage_id,
                          td.task_id.partition_id)
        plan = serde.physical_from_proto(td.plan)
        shuffle = None
        if td.shuffle_output_partitions:
            hx = [serde.expr_from_proto(e) for e in td.shuffle_hash_exprs]
            shuffle = (hx or None, td.shuffle_output_partitions)
        try:
            stats = executor.execute_partition(pid, plan, shuffle)
            executor._report_completed(pid, stats)
        except Exception as e:  # noqa: BLE001 - report like the real loop
            executor._report_failed(pid, f"{type(e).__name__}: {e}")
    return result


class SlowSource(MemTableSource):
    """A MemTableSource whose per-partition scan sleeps first — a
    deterministic window for cooperative-cancellation tests (the
    standalone collect checks its token at every batch boundary)."""

    def __init__(self, inner: MemTableSource, delay_secs: float):
        super().__init__(inner._schema, inner._partitions)
        self._delay = delay_secs

    def scan(self, partition, projection=None):
        time.sleep(self._delay)
        return super().scan(partition, projection)


def _slow_ctx(delay_secs: float = 0.25, parts: int = 4) -> BallistaContext:
    ctx = BallistaContext.standalone()
    inner = MemTableSource.from_pydict(
        TSCHEMA,
        {"a": list(range(64)), "c": [f"k{i % 7}" for i in range(64)]},
        num_partitions=parts,
    )
    ctx.register_source("t", SlowSource(inner, delay_secs))
    return ctx


# ---------------------------------------------------------------------------
# (a) fault-injection layer: parsing, deterministic triggers, lint
# ---------------------------------------------------------------------------


def test_fault_spec_parse_errors_are_loud():
    with pytest.raises(FaultConfigError):
        parse_spec("not.a.point=fail-once")  # unknown point
    with pytest.raises(FaultConfigError):
        parse_spec("shuffle.fetch=banana")  # unknown trigger
    with pytest.raises(FaultConfigError):
        parse_spec("garbage")  # malformed entry
    with pytest.raises(FaultConfigError):
        parse_spec("shuffle.fetch=fail-every:x")  # bad argument
    rules = parse_spec("shuffle.fetch=fail-every:3 , client.rpc=delay:10")
    assert set(rules) == {"shuffle.fetch", "client.rpc"}


def test_fault_triggers_are_deterministic(faults_env):
    # fail-once:K fires on exactly the Kth hit
    faults_env("executor.task.start=fail-once:2")
    assert fault_point("executor.task.start") is None
    with pytest.raises(FaultInjected):
        fault_point("executor.task.start")
    assert fault_point("executor.task.start") is None

    # fail-every:N fires on every Nth hit
    faults_env("executor.task.start=fail-every:3")
    fired = []
    for _ in range(9):
        try:
            fault_point("executor.task.start")
            fired.append(False)
        except FaultInjected:
            fired.append(True)
    assert fired == [False, False, True] * 3

    # drop returns the action for the caller to act on
    faults_env("dataplane.serve=drop-once")
    assert fault_point("dataplane.serve") == "drop"
    assert fault_point("dataplane.serve") is None

    # delay sleeps then reports
    faults_env("state.save=delay:1")
    assert fault_point("state.save") == "delay"

    # disarmed: pure no-op
    faults_env("")
    assert fault_point("shuffle.fetch") is None


def test_fault_points_lint_green():
    """dev/check_fault_points.py: every literal call-site name is
    registered and every registered point has a call site (tier-1, like
    check_metric_names/check_knob_docs)."""
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "dev",
                                      "check_fault_points.py")],
        capture_output=True, text=True,
    )
    assert res.returncode == 0, res.stdout + res.stderr


# ---------------------------------------------------------------------------
# (b) cancellation at the scheduler: terminal state, queue drop, piggyback
# ---------------------------------------------------------------------------


def test_cancel_drops_queued_tasks_and_is_terminal(tmp_path):
    svc = SchedulerService(SchedulerState(MemoryBackend()))
    job_id = _submit_groupby(svc, _source(tmp_path))
    assert any(p.job_id == job_id for p in svc.state._ready)

    res = svc.CancelJob(pb.CancelJobParams(job_id=job_id, reason="client"))
    assert res.cancelled and res.state == "cancelled"
    # queued tasks are gone; the terminal state carries the reason
    assert all(p.job_id != job_id for p in svc.state._ready)
    st = svc.state.get_job_status(job_id)
    assert st.state == "cancelled" and st.cancel_reason == "client"

    # idempotent: a second cancel reports the (unchanged) terminal state
    res2 = svc.CancelJob(pb.CancelJobParams(job_id=job_id))
    assert not res2.cancelled and res2.state == "cancelled"
    # unknown job: no crash, state "unknown"
    res3 = svc.CancelJob(pb.CancelJobParams(job_id="j-nope"))
    assert not res3.cancelled and res3.state == "unknown"

    # GetJobStatus speaks the cancelled oneof with the reason
    gs = svc.GetJobStatus(pb.GetJobStatusParams(job_id=job_id))
    assert gs.status.WhichOneof("status") == "cancelled"
    assert gs.status.cancelled.reason == "client"


def test_cancel_piggybacks_on_poll_and_late_reports_are_dropped(tmp_path):
    svc = SchedulerService(SchedulerState(MemoryBackend()))
    ex = Executor(ExecutorConfig(work_dir=str(tmp_path / "e1"),
                                 scheduler_port=1))
    try:
        job_id = _submit_groupby(svc, _source(tmp_path))
        # run one producer task to completion; its report is PENDING
        res = _pump(svc, ex)
        assert res.HasField("task")

        assert svc.CancelJob(
            pb.CancelJobParams(job_id=job_id, reason="client")).cancelled

        # the next poll (delivering the now-late completion report)
        # carries the cancelled id back; the report must NOT resurrect
        # the job or its dependents
        res2 = _pump(svc, ex, run=False)
        assert job_id in list(res2.cancelled_jobs)
        st = svc.state.get_job_status(job_id)
        assert st.state == "cancelled"
        # nothing re-queued for the cancelled job
        assert all(p.job_id != job_id for p in svc.state._ready)
    finally:
        ex._data_plane.close()
        ex._pool.shutdown(wait=False)


def test_cancelled_id_broadcast_window_is_bounded(tmp_path):
    state = SchedulerState(MemoryBackend())
    state.save_job_status("j1", JobStatus("running"))
    assert state.cancel_job("j1", "client")
    assert state.cancelled_job_ids() == ["j1"]
    # age the entry past the broadcast window: pruned
    with state._lock:
        state._cancelled_jobs["j1"] -= state.CANCEL_BROADCAST_SECS + 1
    assert state.cancelled_job_ids() == []
    # the terminal state is still visible (KV, not the broadcast set)
    assert state.is_job_cancelled("j1")


# ---------------------------------------------------------------------------
# (c) server-side deadlines + slow-query kill (reap pass)
# ---------------------------------------------------------------------------


def test_deadline_expiry_cancels_job(tmp_path):
    svc = SchedulerService(SchedulerState(MemoryBackend()))
    job_id = _submit_groupby(svc, _source(tmp_path), deadline_secs=0.05)
    assert svc.state.get_job_deadline(job_id) is not None
    time.sleep(0.1)
    reaped = svc.state.reap_expired_jobs(min_interval_secs=0.0)
    assert job_id in reaped
    st = svc.state.get_job_status(job_id)
    assert st.state == "cancelled" and st.cancel_reason == "deadline"
    # terminal transition cleared the deadline entry
    assert svc.state.get_job_deadline(job_id) is None


def test_deadline_enforced_with_no_executors(tmp_path):
    """With every executor down there are no PollWork calls; the reap
    pass must still fire off the waiting client's GetJobStatus polls so
    the deadline holds."""
    state = SchedulerState(MemoryBackend())
    server, svc, port = serve_scheduler(state, "localhost", 0)
    try:
        job_id = _submit_groupby(svc, _source(tmp_path), deadline_secs=0.2)
        from ballista_tpu.distributed.client import wait_for_job

        with pytest.raises(QueryCancelled) as ei:
            wait_for_job("localhost", port, job_id, timeout=10)
        assert ei.value.reason == "deadline" and ei.value.job_id == job_id
    finally:
        server.stop(grace=None)


def test_slow_query_kill_reaps_overdue_jobs(tmp_path, monkeypatch):
    monkeypatch.setenv("BALLISTA_SLOW_QUERY_KILL_SECS", "0.05")
    svc = SchedulerService(SchedulerState(MemoryBackend()))
    job_id = _submit_groupby(svc, _source(tmp_path))
    time.sleep(0.1)
    reaped = svc.state.reap_expired_jobs(min_interval_secs=0.0)
    assert job_id in reaped
    st = svc.state.get_job_status(job_id)
    assert st.state == "cancelled" and st.cancel_reason == "slow-query-kill"


# ---------------------------------------------------------------------------
# (d) executor: drain flushes pending reports; poll backoff
# ---------------------------------------------------------------------------


def test_drain_flushes_pending_status(tmp_path):
    """A drained executor's last word: completion reports pending at
    stop(drain=True) reach the scheduler in the final flush even though
    the poll loop never runs again."""
    state = SchedulerState(MemoryBackend())
    server, svc, port = serve_scheduler(state, "localhost", 0)
    ex = None
    try:
        state.save_job_status("j1", JobStatus("running"))
        state.save_stage_plan("j1", 1, b"", 1, [])
        ex = Executor(ExecutorConfig(work_dir=str(tmp_path / "w"),
                                     scheduler_port=port))
        pid = PartitionId("j1", 1, 0)
        ex._report_completed(
            pid, {"path": "/w/data.arrow", "num_rows": 3, "num_bytes": 64})
        assert state.get_task_statuses("j1", 1) == []  # not delivered yet
        ex.stop(drain=True, drain_timeout=0.05)
        (st,) = state.get_task_statuses("j1", 1)
        assert st.state == "completed" and st.path == "/w/data.arrow"
    finally:
        if ex is not None:
            ex._pool.shutdown(wait=False)
        server.stop(grace=None)


def test_poll_backoff_no_log_storm(caplog):
    """While the scheduler is down the poll loop backs off with jitter
    and logs ONE traceback + one-line repeats — not a full traceback
    every 250ms (thundering-herd / log-storm guard)."""
    ex = Executor(ExecutorConfig(scheduler_port=1))  # nothing listens
    logger = logging.getLogger("ballista.executor")
    old_propagate = logger.propagate
    logger.propagate = True
    try:
        with caplog.at_level(logging.WARNING, logger="ballista.executor"):
            ex.start()
            time.sleep(1.3)
            ex.stop()
    finally:
        logger.propagate = old_propagate
    polls = [r for r in caplog.records
             if "poll" in r.getMessage() or "backing off" in r.getMessage()]
    with_tb = [r for r in polls if r.exc_info]
    assert len(with_tb) == 1, \
        f"expected ONE traceback, got {len(with_tb)} of {len(polls)}"
    assert any("still failing" in r.getMessage() for r in polls)
    # backoff actually spaced the retries: ~1.3s of downtime at 250ms
    # fixed interval would be ~5 failures; backoff caps it lower
    assert len(polls) <= 4


# ---------------------------------------------------------------------------
# (e) standalone path: ctx/df cancel + slow-query kill + system.queries
# ---------------------------------------------------------------------------


def test_standalone_cancel_from_another_thread():
    ctx = _slow_ctx(delay_secs=0.25, parts=4)
    df = ctx.sql("select a, c from t")
    box = {}

    def run():
        try:
            box["out"] = df.collect()
        except BaseException as e:  # noqa: BLE001 - captured for asserts
            box["err"] = e

    th = threading.Thread(target=run)
    th.start()
    _wait_until(lambda: ctx._active_tokens, 5, "collect never registered")
    assert ctx.cancel("client") == 1
    th.join(20)
    assert not th.is_alive(), "collect hung after cancel"
    err = box.get("err")
    assert isinstance(err, QueryCancelled) and err.reason == "client"

    # terminal record lands in system.queries as cancelled + reason
    rows = ctx.sql(
        "select status, cancel_reason from system.queries").collect()
    cancelled = rows[rows["status"] == "cancelled"]
    assert len(cancelled) >= 1
    assert "client" in set(cancelled["cancel_reason"])

    # the context stays usable: the SAME query completes afterwards
    out = ctx.sql("select sum(a) as s from t").collect()
    assert int(out["s"][0]) == sum(range(64))


def test_standalone_slow_query_kill(monkeypatch):
    monkeypatch.setenv("BALLISTA_SLOW_QUERY_KILL_SECS", "0.1")
    ctx = _slow_ctx(delay_secs=0.25, parts=4)
    with pytest.raises(QueryCancelled) as ei:
        ctx.sql("select a, c from t").collect()
    assert ei.value.reason == "slow-query-kill"
    monkeypatch.delenv("BALLISTA_SLOW_QUERY_KILL_SECS")
    rows = ctx.sql(
        "select status, cancel_reason from system.queries").collect()
    assert "slow-query-kill" in set(
        rows[rows["status"] == "cancelled"]["cancel_reason"])


# ---------------------------------------------------------------------------
# (f) e2e gates on a real LocalCluster
# ---------------------------------------------------------------------------


def test_cancel_mid_stage_e2e(tmp_path, faults_env):
    """THE e2e gate: a job cancelled mid-stage reaches Cancelled in
    system.queries, its executors' slots free within 5s, and a
    follow-up job on the same cluster completes byte-identical."""
    path = _write_tbl(tmp_path)
    cluster = LocalCluster(num_executors=2, concurrent_tasks=2)
    try:
        ctx = _remote_ctx(cluster)
        ctx.register_tbl("t", path, TSCHEMA)
        # every task start sleeps 600ms: a deterministic mid-stage window
        faults_env("executor.task.start=delay:600")
        box = {}

        def run():
            try:
                box["out"] = ctx.sql(GROUPBY_SQL).collect()
            except BaseException as e:  # noqa: BLE001 - captured
                box["err"] = e

        th = threading.Thread(target=run)
        th.start()
        _wait_until(lambda: any(e._task_tokens for e in cluster.executors),
                    10, "no task ever started")
        assert ctx.cancel("client") >= 1
        th.join(20)
        assert not th.is_alive(), "collect hung after cancel"
        err = box.get("err")
        assert isinstance(err, QueryCancelled), f"got {box}"
        job_id = err.job_id
        assert job_id

        st = cluster.state.get_job_status(job_id)
        assert st.state == "cancelled" and st.cancel_reason == "client"

        # executor slots free within 5s (tokens fired at the next poll,
        # tasks aborted at their batch boundary)
        _wait_until(
            lambda: all(not e._task_tokens and e._inflight == 0
                        for e in cluster.executors),
            5, "executor slots not freed within 5s of cancel")

        # system.queries (fetched from the scheduler) has the terminal
        # cancelled record with its reason
        rows = ctx.sql("select job_id, status, cancel_reason "
                       "from system.queries").collect()
        rec = rows[rows["job_id"] == job_id]
        assert len(rec) == 1
        assert rec["status"].iloc[0] == "cancelled"
        assert rec["cancel_reason"].iloc[0] == "client"

        # follow-up job on the SAME cluster: byte-identical
        faults_env("")
        _assert_identical(ctx.sql(GROUPBY_SQL).collect(), _expected())
    finally:
        faults_env("")
        cluster.shutdown()


def test_server_deadline_e2e(tmp_path, faults_env):
    path = _write_tbl(tmp_path)
    cluster = LocalCluster(num_executors=2, concurrent_tasks=2)
    try:
        ctx = _remote_ctx(cluster, **{"job.deadline": "0.5"})
        ctx.register_tbl("t", path, TSCHEMA)
        faults_env("executor.task.start=delay:700")
        t0 = time.time()
        with pytest.raises(QueryCancelled) as ei:
            ctx.sql(GROUPBY_SQL).collect()
        assert ei.value.reason == "deadline"
        # terminated within the deadline plus reap/poll slack
        assert time.time() - t0 < 15
        st = cluster.state.get_job_status(ei.value.job_id)
        assert st.state == "cancelled" and st.cancel_reason == "deadline"
    finally:
        faults_env("")
        cluster.shutdown()


def test_client_timeout_issues_best_effort_cancel(tmp_path, faults_env,
                                                  monkeypatch):
    path = _write_tbl(tmp_path)
    cluster = LocalCluster(num_executors=2, concurrent_tasks=2)
    try:
        ctx = _remote_ctx(cluster, **{"job.timeout": "0.8"})
        ctx.register_tbl("t", path, TSCHEMA)
        faults_env("executor.task.start=delay:700")
        with pytest.raises(ClusterError) as ei:
            ctx.sql(GROUPBY_SQL).collect()
        # the error carries the job id for system.queries triage
        job_id = ei.value.job_id
        assert job_id
        # ... and the scheduler moves the abandoned job to cancelled
        _wait_until(
            lambda: cluster.state.get_job_status(job_id).state
            == "cancelled",
            5, "timed-out job was never cancelled")
        assert cluster.state.get_job_status(job_id).cancel_reason \
            == "timeout"

        # knob off: the old abandon-the-job behavior (job keeps running)
        monkeypatch.setenv("BALLISTA_CANCEL_ON_TIMEOUT", "0")
        with pytest.raises(ClusterError) as ei2:
            ctx.sql("select c, sum(a) as s2 from t group by c").collect()
        job2 = ei2.value.job_id
        st = cluster.state.get_job_status(job2)
        assert st.state in ("queued", "running")
        # clean up so shutdown doesn't wait on it
        monkeypatch.delenv("BALLISTA_CANCEL_ON_TIMEOUT")
        cluster.service.CancelJob(pb.CancelJobParams(job_id=job2))
    finally:
        faults_env("")
        cluster.shutdown()


def test_graceful_drain_migrates_inflight_task(tmp_path, faults_env):
    """stop(drain=True): the draining executor stops accepting, cancels
    its in-flight task at the bound, its reports are flushed, and the
    job COMPLETES on the remaining executor (drain-cancelled attempts
    are transient-shaped, so the scheduler re-queues them)."""
    path = _write_tbl(tmp_path)
    cluster = LocalCluster(num_executors=2, concurrent_tasks=1)
    try:
        ctx = _remote_ctx(cluster)
        ctx.register_tbl("t", path, TSCHEMA)
        faults_env("executor.task.start=delay:800")
        box = {}

        def run():
            try:
                box["out"] = ctx.sql(GROUPBY_SQL).collect()
            except BaseException as e:  # noqa: BLE001 - captured
                box["err"] = e

        th = threading.Thread(target=run)
        th.start()
        drained = cluster.executors[0]
        _wait_until(lambda: drained._task_tokens, 10,
                    "executor 0 never picked up a task")
        drained.stop(drain=True, drain_timeout=0.05)
        assert drained.tasks_cancelled >= 1
        th.join(45)
        assert not th.is_alive(), "job hung after drain"
        assert "err" not in box, f"job failed after drain: {box.get('err')}"
        _assert_identical(box["out"], _expected())
    finally:
        faults_env("")
        cluster.shutdown()


# ---------------------------------------------------------------------------
# (g) recovery-on-faults: the hand-rolled shuffle loss, now injected
# ---------------------------------------------------------------------------


def test_shuffle_fetch_fault_rides_retry_and_recovery(tmp_path, faults_env,
                                                      monkeypatch):
    """Port of test_recovery's hand-crafted shuffle-loss setup onto the
    fault layer: an injected fetch failure takes the SAME tagged
    ShuffleFetchError path (in-task retry first, producer re-queue
    beyond it) — no work_dir deletion or fake statuses needed."""
    monkeypatch.setattr(ShuffleReaderExec, "FORCE_REMOTE", True)
    path = _write_tbl(tmp_path)
    cluster = LocalCluster(num_executors=2, concurrent_tasks=2)
    try:
        ctx = _remote_ctx(cluster)
        ctx.register_tbl("t", path, TSCHEMA)
        faults_env("shuffle.fetch=fail-once")
        _assert_identical(ctx.sql(GROUPBY_SQL).collect(), _expected())
        # the armed rule genuinely fired (vacuous pass guard)
        assert faults_mod._rules["shuffle.fetch"].hits >= 1
    finally:
        faults_env("")
        cluster.shutdown()


# ---------------------------------------------------------------------------
# (h) chaos sweep: deterministic fault configs on a LocalCluster
# ---------------------------------------------------------------------------

# seed -> (BALLISTA_FAULTS spec, extra ctx settings, env overrides).
# Outcome law (the chaos gate): every job either completes
# byte-identical or terminates cleanly (Failed/Cancelled) within its
# deadline — zero hangs, retry budgets respected. "must_complete" seeds
# additionally REQUIRE the identical completion (the injected fault is
# within the engine's recovery envelope).
CHAOS_SEEDS = {
    "baseline": ("", {}, {}, True),
    "task-fail-once": ("executor.task.start=fail-once", {}, {}, True),
    "task-fail-every-3": ("executor.task.start=fail-every:3", {}, {},
                          False),
    "shuffle-fail-once": ("shuffle.fetch=fail-once:2", {}, {}, True),
    "shuffle-fail-always": ("shuffle.fetch=fail-every:1", {}, {}, False),
    "poll-fail-once": ("scheduler.poll_work=fail-once:3", {}, {}, True),
    "state-save-fail": ("state.save=fail-once:4", {}, {}, False),
    "rpc-delay": ("client.rpc=delay:25", {}, {}, True),
    "task-delay-deadline": ("executor.task.start=delay:400",
                            {"job.deadline": "1.0"}, {}, False),
    "dataplane-drop": ("dataplane.serve=drop-once", {},
                       {"BALLISTA_NATIVE_DATAPLANE": "off"}, False),
    # live progress plane: dropped or delayed TaskProgress piggybacks
    # are advisory by contract — results MUST stay byte-identical (the
    # tight interval forces every poll to attempt a sample)
    "progress-drop": ("scheduler.progress_report=drop-every:1", {},
                      {"BALLISTA_PROGRESS_INTERVAL_SECS": "0.05"}, True),
    "progress-delay": ("scheduler.progress_report=delay:50", {},
                       {"BALLISTA_PROGRESS_INTERVAL_SECS": "0.05"}, True),
    "progress-fail": ("scheduler.progress_report=fail-every:1", {},
                      {"BALLISTA_PROGRESS_INTERVAL_SECS": "0.05"}, True),
    # streaming shuffle data plane (docs/shuffle.md): chunk-level
    # faults on the flow-controlled stream. Tiny chunk size forces
    # multi-chunk streams on this small table.
    "stream-chunk-fail": ("shuffle.stream.chunk=fail-once:2", {},
                          {"BALLISTA_SHUFFLE_CHUNK_BYTES": "1024"}, True),
    "stream-chunk-delay": ("shuffle.stream.chunk=delay:40", {},
                           {"BALLISTA_SHUFFLE_CHUNK_BYTES": "1024"}, True),
    # mid-stream executor death: the serving side closes the connection
    # between chunks (drop), or streams a tagged error frame every time
    # (fail) — recovery must re-queue the producer or terminate cleanly
    "flow-drop-midstream": ("dataplane.flow=drop-once:2", {},
                            {"BALLISTA_NATIVE_DATAPLANE": "off",
                             "BALLISTA_SHUFFLE_CHUNK_BYTES": "1024"}, True),
    "flow-fail-always": ("dataplane.flow=fail-every:1", {},
                         {"BALLISTA_NATIVE_DATAPLANE": "off",
                          "BALLISTA_SHUFFLE_CHUNK_BYTES": "1024"}, False),
    # spill lane: a tiny budget forces every fetched chunk to disk —
    # results must stay byte-identical streaming-from-disk
    "spill-forced": ("", {},
                     {"BALLISTA_SHUFFLE_MEM_BUDGET": "4096",
                      "BALLISTA_SHUFFLE_CHUNK_BYTES": "1024"}, True),
    # torn spill write (drop = half the payload reaches disk): the
    # replay detects the corrupt segment, the fetch retries and the
    # second attempt's spill is clean — truncated-spill recovery
    "spill-torn-write": ("shuffle.spill.write=drop-once", {},
                         {"BALLISTA_SHUFFLE_MEM_BUDGET": "4096",
                          "BALLISTA_SHUFFLE_CHUNK_BYTES": "1024"}, True),
    "spill-write-fail": ("shuffle.spill.write=fail-once", {},
                         {"BALLISTA_SHUFFLE_MEM_BUDGET": "4096",
                          "BALLISTA_SHUFFLE_CHUNK_BYTES": "1024"}, True),
    # admission plane (PR 15): a gate fault sheds the submission with a
    # structured retryable error; remote_collect honors the retry-after
    # and the resubmission completes byte-identical. A gate delay just
    # slows ExecuteQuery. (Queue-pump faults are exercised by the
    # overload sweep in test_admission.py, where a queue exists.)
    "admit-fail-once": ("scheduler.admit=fail-once", {}, {}, True),
    "admit-delay": ("scheduler.admit=delay:100", {}, {}, True),
}


@pytest.mark.parametrize("seed", sorted(CHAOS_SEEDS))
def test_chaos_sweep(tmp_path, faults_env, monkeypatch, seed):
    spec, extra_settings, env, must_complete = CHAOS_SEEDS[seed]
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    # shuffle reads must cross the data plane for fetch/serve faults
    monkeypatch.setattr(ShuffleReaderExec, "FORCE_REMOTE", True)
    path = _write_tbl(tmp_path)
    cluster = LocalCluster(num_executors=2, concurrent_tasks=2)
    try:
        ctx = _remote_ctx(cluster, **{"job.timeout": "45",
                                      **extra_settings})
        ctx.register_tbl("t", path, TSCHEMA)
        faults_env(spec)
        box = {}

        def run():
            try:
                box["out"] = ctx.sql(GROUPBY_SQL).collect()
            except BaseException as e:  # noqa: BLE001 - captured
                box["err"] = e

        t0 = time.time()
        th = threading.Thread(target=run)
        th.start()
        th.join(60)
        elapsed = time.time() - t0
        assert not th.is_alive(), f"seed {seed}: HUNG after {elapsed:.0f}s"

        if "out" in box:
            _assert_identical(box["out"], _expected())
        else:
            err = box["err"]
            assert isinstance(err, (ClusterError, QueryCancelled)), \
                f"seed {seed}: dirty failure {type(err).__name__}: {err}"
            assert not must_complete, \
                f"seed {seed}: expected completion, got {err}"
            if isinstance(err, QueryCancelled):
                # a deadline kill must land near its deadline, not at
                # the client timeout
                assert elapsed < 20
            # retry budgets respected: never more than budget+1 attempts
            jid = getattr(err, "job_id", None)
            if jid:
                assert cluster.state._recovery_count(jid) <= \
                    cluster.state.MAX_RECOVERIES_PER_JOB + 1
    finally:
        faults_env("")
        cluster.shutdown()


# ---------------------------------------------------------------------------
# (i) overhead gate: disabled fault points + cancel-token machinery < 5%
# ---------------------------------------------------------------------------


def test_lifecycle_overhead_q1_under_5pct(tmp_path_factory, faults_env):
    """Drift-cancelling overhead gate (same method as the metrics gate
    in test_observability): warm q1 through the full lifecycle wrapper
    (token + bind + killer no-op + tracked registration) with an
    armed-but-idle fault spec, vs the bare governed collect with faults
    disarmed. Interleaved alternating samples + medians cancel machine
    drift; <5% (+2ms floor) or fail."""
    from benchmarks.tpch import datagen
    from benchmarks.tpch.schema_def import register_tpch

    data_dir = str(tmp_path_factory.mktemp("tpch_lc"))
    datagen.generate(data_dir, scale=0.01, num_parts=1)
    ctx = BallistaContext.standalone()
    register_tpch(ctx, data_dir, "tbl")
    qdir = os.path.join(REPO, "benchmarks", "tpch", "queries")
    df = ctx.sql(open(os.path.join(qdir, "q1.sql")).read())
    df.collect()  # warm: jit compile + table caches
    plan, phys = df.plan, df._phys

    # a rule that can never fire: hit ceiling far beyond the run count
    IDLE_SPEC = "executor.task.start=fail-once:1000000000"

    def sample(on: bool) -> float:
        faults_env(IDLE_SPEC if on else "")
        t0 = time.perf_counter()
        for _ in range(3):
            if on:
                ctx._standalone_collect_inner(plan, phys)
            else:
                ctx._standalone_collect_governed(plan, phys)
        return time.perf_counter() - t0

    sample(True)
    sample(False)  # settle both paths before measuring

    def measure():
        offs, ons = [], []
        for i in range(9):
            if i % 2 == 0:
                offs.append(sample(False))
                ons.append(sample(True))
            else:
                ons.append(sample(True))
                offs.append(sample(False))
        return sorted(offs)[4], sorted(ons)[4]

    for _ in range(3):
        t_off, t_on = measure()
        if t_on <= t_off * 1.05 + 2e-3:
            return
    overhead = (t_on - t_off) / t_off
    raise AssertionError(
        f"lifecycle overhead {overhead:.1%} "
        f"(on={t_on:.4f}s off={t_off:.4f}s)")
