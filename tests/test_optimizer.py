"""Optimizer rewrite unit tests: direct plan-shape coverage.

The TPC-H oracle suite exercises only the shapes those 22 queries
happen to contain, so each rewrite is pinned here directly:
``push_semi_joins`` guard branches (push-left, push-right, the
name-collision left-wins rule, the pruning-other-side suppression),
``push_filters`` conjunct sinking, and ``prune_columns`` reaching
scans.
"""

import numpy as np

from ballista_tpu import schema, Int64, lit, col
from ballista_tpu import expr as ex
from ballista_tpu.io import MemTableSource
from ballista_tpu.logical import Filter, Join, Projection, TableScan
from ballista_tpu.optimizer import (
    prune_columns,
    push_filters,
    push_semi_joins,
)


def _scan(name, cols, n=10):
    s = schema(*[(c, Int64) for c in cols])
    src = MemTableSource.from_pydict(
        s, {c: np.arange(n) for c in cols})
    return TableScan(name, src)


def _sub():
    return _scan("s", ["sk"], n=3)


def test_push_left_through_inner():
    a, b = _scan("a", ["ak", "x"]), _scan("b", ["bk", "y"])
    inner = Join(a, b, on=[("ak", "bk")], how="inner")
    plan = Join(inner, _sub(), on=[("x", "sk")], how="semi")
    out = push_semi_joins(plan)
    assert isinstance(out, Join) and out.how == "inner"
    assert isinstance(out.left, Join) and out.left.how == "semi"
    assert out.left.left is a
    assert out.right is b


def test_push_right_through_inner():
    a, b = _scan("a", ["ak", "x"]), _scan("b", ["bk", "y"])
    inner = Join(a, b, on=[("ak", "bk")], how="inner")
    plan = Join(inner, _sub(), on=[("y", "sk")], how="anti",
                null_aware=True)
    out = push_semi_joins(plan)
    assert isinstance(out, Join) and out.how == "inner"
    assert isinstance(out.right, Join) and out.right.how == "anti"
    assert out.right.null_aware  # flag rides the pushed join
    assert out.right.left is b
    assert out.left is a


def test_collision_resolves_left_only():
    # both inputs expose column "k"; the inner join's output keeps the
    # LEFT one, so a semi keyed on "k" may only push left
    a, b = _scan("a", ["k", "ak"]), _scan("b", ["k", "bk"])
    inner = Join(a, b, on=[("ak", "bk")], how="inner")
    plan = Join(inner, _sub(), on=[("k", "sk")], how="semi")
    out = push_semi_joins(plan)
    assert out.how == "inner"
    assert isinstance(out.left, Join) and out.left.how == "semi"
    assert out.left.left is a  # never lands on b despite b also having k


def test_no_push_when_other_side_prunes():
    # the other inner-join input carries a filter: its join may shrink
    # the key side below the pre-join table, so placement stays hoisted
    a = _scan("a", ["ak", "x"])
    b = Filter(col("bk") > lit(2), _scan("b", ["bk", "y"]))
    inner = Join(a, b, on=[("ak", "bk")], how="inner")
    plan = Join(inner, _sub(), on=[("x", "sk")], how="semi")
    out = push_semi_joins(plan)
    # unchanged shape: semi stays above the join
    assert out.how == "semi" and out.left.how == "inner"


def test_filter_conjuncts_sink_to_join_sides():
    a, b = _scan("a", ["ak", "x"]), _scan("b", ["bk", "y"])
    inner = Join(a, b, on=[("ak", "bk")], how="inner")
    pred = ((col("x") > lit(1)) & (col("y") > lit(2))
            & (col("x") < col("y")))
    out = push_filters(Filter(pred, inner))
    # cross-side conjunct (references both inputs) stays above the join
    assert isinstance(out, Filter)
    assert set(ex.referenced_columns(out.predicate)) == {"x", "y"}
    j = out.input
    assert isinstance(j, Join)
    # single-side conjuncts sank to exactly their own input, undoubled
    assert isinstance(j.left, Filter)
    assert set(ex.referenced_columns(j.left.predicate)) == {"x"}
    assert isinstance(j.right, Filter)
    assert set(ex.referenced_columns(j.right.predicate)) == {"y"}


def test_prune_columns_reaches_scans():
    a, b = _scan("a", ["ak", "x", "unused1"]), _scan("b", ["bk", "y", "unused2"])
    inner = Join(a, b, on=[("ak", "bk")], how="inner")
    plan = Projection([ex.ColumnRef("x"), ex.ColumnRef("y")], inner)
    out = prune_columns(plan, None)
    scans = []

    def walk(p):
        if isinstance(p, TableScan):
            scans.append(p)
        for c in p.children():
            walk(c)

    walk(out)
    got = {s.table_name: set(s.projection or s.schema().names())
           for s in scans}
    # join keys + referenced columns only; unused columns pruned
    assert got["a"] == {"ak", "x"}, got
    assert got["b"] == {"bk", "y"}, got


def test_no_push_through_outer_join():
    a, b = _scan("a", ["ak", "x"]), _scan("b", ["bk", "y"])
    left = Join(a, b, on=[("ak", "bk")], how="left")
    plan = Join(left, _sub(), on=[("x", "sk")], how="semi")
    out = push_semi_joins(plan)
    assert out.how == "semi" and out.left.how == "left"
