"""Scalar-function parity with the reference's ScalarFunction enum.

The reference declares 33 scalar functions (reference:
rust/core/proto/ballista.proto:80-114). This file covers the ones added
for parity in round 3: OCTETLENGTH, MD5/SHA224/SHA256/SHA384/SHA512,
DATETRUNC, TOTIMESTAMP — evaluated through the full SQL path.
"""

import hashlib

import numpy as np
import pytest

from ballista_tpu import schema, Int64, Utf8
from ballista_tpu.client import BallistaContext
from ballista_tpu.datatypes import Date32


@pytest.fixture()
def ctx():
    c = BallistaContext.standalone()
    c.register_memtable(
        "t", schema(("k", Int64), ("s", Utf8), ("d", Date32)),
        {
            "k": [1, 2, 3],
            "s": ["héllo", "world", "x"],
            # days since epoch: 2024-02-15, 1999-12-31, 1970-01-01
            "d": np.array(["2024-02-15", "1999-12-31", "1970-01-05"],
                          dtype="datetime64[D]").astype(np.int32),
        },
        primary_key="k",
    )
    return c


def test_octet_length_vs_char_length(ctx):
    out = ctx.sql(
        "select k, length(s) as cl, octet_length(s) as ol from t order by k"
    ).collect()
    assert list(out["cl"]) == [5, 5, 1]
    assert list(out["ol"]) == [6, 5, 1]  # é is 2 bytes in UTF-8


@pytest.mark.parametrize("fn", ["md5", "sha224", "sha256", "sha384", "sha512"])
def test_hash_functions(ctx, fn):
    out = ctx.sql(f"select k, {fn}(s) as h from t order by k").collect()
    expect = [getattr(hashlib, fn)(s.encode()).hexdigest()
              for s in ["héllo", "world", "x"]]
    assert list(out["h"]) == expect


def test_date_trunc_month_year(ctx):
    out = ctx.sql(
        "select k, date_trunc('month', d) as m, date_trunc('year', d) as y, "
        "date_trunc('quarter', d) as q from t order by k"
    ).collect()
    assert [str(v)[:10] for v in out["m"]] == [
        "2024-02-01", "1999-12-01", "1970-01-01"]
    assert [str(v)[:10] for v in out["y"]] == [
        "2024-01-01", "1999-01-01", "1970-01-01"]
    assert [str(v)[:10] for v in out["q"]] == [
        "2024-01-01", "1999-10-01", "1970-01-01"]


def test_date_trunc_week(ctx):
    # 2024-02-15 is a Thursday -> Monday 2024-02-12
    out = ctx.sql(
        "select k, date_trunc('week', d) as w from t order by k"
    ).collect()
    assert str(out["w"][0])[:10] == "2024-02-12"


def test_to_timestamp_parses_iso_strings(ctx):
    ctx.register_memtable(
        "ts", schema(("k", Int64), ("raw", Utf8)),
        {"k": [1, 2, 3],
         "raw": ["2023-05-01T12:30:00", "2020-01-01", "not a time"]},
    )
    out = ctx.sql(
        "select k, to_timestamp(raw) as t from ts order by k"
    ).collect()
    assert str(out["t"][0]) == "2023-05-01 12:30:00"
    assert str(out["t"][1]) == "2020-01-01 00:00:00"
    assert str(out["t"][2]) == "NaT"  # unparseable -> NULL


def test_date_part_on_timestamp(ctx):
    ctx.register_memtable(
        "ts3", schema(("k", Int64), ("raw", Utf8)),
        {"k": [1], "raw": ["2023-05-07T12:30:00"]},
    )
    out = ctx.sql(
        "select date_part('year', to_timestamp(raw)) as y, "
        "date_part('month', to_timestamp(raw)) as m, "
        "date_part('day', to_timestamp(raw)) as d from ts3"
    ).collect()
    assert (out["y"][0], out["m"][0], out["d"][0]) == (2023, 5, 7)


def test_date_trunc_on_timestamp(ctx):
    ctx.register_memtable(
        "ts2", schema(("k", Int64), ("raw", Utf8)),
        {"k": [1], "raw": ["2023-05-07T12:30:00"]},
    )
    out = ctx.sql(
        "select date_trunc('month', to_timestamp(raw)) as m from ts2"
    ).collect()
    assert str(out["m"][0]) == "2023-05-01 00:00:00"


def test_timestamp_ddl_and_scan(tmp_path):
    """A timestamp column declared through DDL must scan (pandas CSV
    path), round-trip precision, and support sub-day trunc/extract."""
    p = tmp_path / "events.csv"
    p.write_text("ts,v\n2024-01-02T10:30:45,1\n2262-04-12T00:00:00,2\n")
    c = BallistaContext.standalone()
    c.sql(f"create external table events (ts timestamp, v int) "
          f"with header row stored as csv location '{p}'")
    out = c.sql(
        "select date_trunc('hour', ts) as h, date_part('minute', ts) as m, "
        "v from events order by v"
    ).collect()
    assert str(out["h"][0]) == "2024-01-02 10:00:00"
    assert out["m"][0] == 30


def test_to_timestamp_out_of_ns_range_is_null(ctx):
    ctx.register_memtable(
        "far", schema(("s", Utf8)),
        {"s": ["9999-12-31", "1500-01-01", "2024-06-01"]},
    )
    out = ctx.sql("select to_timestamp(s) as t from far").collect()
    # outside the ns-representable range (1678..2262) -> NULL, not wrap
    assert str(out["t"][0]) == "NaT"
    assert str(out["t"][1]) == "NaT"
    assert str(out["t"][2]) == "2024-06-01 00:00:00"
