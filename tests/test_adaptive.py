"""Adaptive query execution (AQE) tests.

Layers, bottom-up: pure rule decision functions on synthetic histograms;
config resolution (settings > env > default, per-rule gates); serde
round-trips of the new wire fields; the ShuffleReaderExec partitioning
fix; stage-version bookkeeping; standalone rewrites; cluster e2e for
each rule (fewer tasks dispatched, identical rows); and an AQE-on vs
AQE-off determinism sweep over the TPC-H tier-1 queries. Also hosts the
proto<->pb2 drift guard (dev/check_proto_sync.py) so it runs in tier-1.
"""

import os
import sys

import numpy as np
import pytest

from ballista_tpu import schema, col, sum_, Int64, Decimal, Utf8
from ballista_tpu.adaptive import AdaptiveConfig
from ballista_tpu.adaptive.rules import (
    describe_layout,
    layout_has_splits,
    plan_shuffle_reads,
    should_broadcast,
)
from ballista_tpu.client import BallistaContext
from ballista_tpu.io import TblSource


MB = 1024 * 1024


def conf(**kw):
    return AdaptiveConfig(**kw)


# ---------------------------------------------------------------------------
# Rule decision functions (synthetic StageMetrics histograms)
# ---------------------------------------------------------------------------


def test_coalesce_merges_small_partitions():
    c = conf(target_partition_bytes=100)
    layout = plan_shuffle_reads([10] * 8, c)
    assert layout == [[(0, 8, 0, 0)]]
    assert describe_layout(8, layout) == "coalesced 8→1"


def test_coalesce_respects_target_and_adjacency():
    c = conf(target_partition_bytes=100)
    layout = plan_shuffle_reads([60, 30, 30, 90, 10], c)
    # greedy adjacent grouping: 60+30 <= 100 | 30 (next would overflow)
    # | 90+10 <= 100
    assert layout == [[(0, 2, 0, 0)], [(2, 3, 0, 0)], [(3, 5, 0, 0)]]


def test_coalesce_identity_returns_none():
    c = conf(target_partition_bytes=100)
    assert plan_shuffle_reads([200, 150, 300], c) is None
    assert plan_shuffle_reads([], c) is None
    assert plan_shuffle_reads([10] * 4, conf(enabled=False)) is None
    assert plan_shuffle_reads([10] * 4, conf(coalesce=False)) is None


def test_skew_splits_by_producer_subranges():
    c = conf(target_partition_bytes=100, skew_factor=2.0)
    producer_bytes = [[10, 10, 10, 10]] * 3 + [[200, 200, 5, 0]]
    layout = plan_shuffle_reads([40, 40, 40, 405], c,
                                producer_bytes=producer_bytes)
    plain = [r for ranges in layout for r in ranges if r[3] == 0]
    splits = [r for ranges in layout for r in ranges if r[3] != 0]
    # non-skewed buckets coalesce; the skewed bucket 3 splits into
    # producer subranges that cover [0, 4) exactly once
    assert all(r[0] == 3 and r[1] == 4 for r in splits)
    assert len(splits) >= 2
    assert splits[0][2] == 0 and splits[-1][3] == 4
    for a, b in zip(splits, splits[1:]):
        assert a[3] == b[2]
    assert plain and layout_has_splits(layout)
    assert "split skewed partition" in describe_layout(4, layout)


def test_skew_guards():
    c = conf(target_partition_bytes=100, skew_factor=2.0)
    # needs >= 2 contributing producers
    one_producer = [[10, 0]] * 3 + [[400, 0]]
    layout = plan_shuffle_reads([10, 10, 10, 400], c,
                                producer_bytes=one_producer)
    assert layout is None or not layout_has_splits(layout)
    # caller veto (allow_skew=False): aggregation consumers
    many = [[10] * 4] * 3 + [[100] * 4]
    layout = plan_shuffle_reads([10, 10, 10, 400], c, producer_bytes=many,
                                allow_skew=False)
    assert layout is None or not layout_has_splits(layout)
    # skew gate off
    layout = plan_shuffle_reads([10, 10, 10, 400],
                                conf(target_partition_bytes=100,
                                     skew_factor=2.0, skew=False),
                                producer_bytes=many)
    assert layout is None or not layout_has_splits(layout)


def test_split_producers_mass_on_last_producer():
    """Regression: mass concentrated on the LAST producer must still
    produce >= 2 covering ranges, never a single all-producer range
    masquerading as a split."""
    from ballista_tpu.adaptive.rules import _split_producers

    ranges = _split_producers([1, 0, 0, 1000], 100)
    assert len(ranges) >= 2
    assert ranges[0][0] == 0 and ranges[-1][1] == 4
    for a, b in zip(ranges, ranges[1:]):
        assert a[1] == b[0]


def test_skew_detected_on_skew_bytes_not_combined():
    """Regression: a bucket heavy on the (replicated) build side but
    light on the probe side must NOT split — each split sub-task
    re-reads the whole build bucket."""
    c = conf(target_partition_bytes=100, skew_factor=2.0)
    combined = [40, 40, 40, 600]       # bucket 3 heavy overall...
    probe_only = [20, 20, 20, 30]      # ...but light on the probe side
    producer_bytes = [[10, 10]] * 3 + [[15, 15]]
    layout = plan_shuffle_reads(combined, c, producer_bytes=producer_bytes,
                                skew_bytes=probe_only)
    assert layout is None or not layout_has_splits(layout)
    # probe-heavy bucket still splits
    probe_heavy = [20, 20, 20, 600]
    producer_bytes = [[10, 10]] * 3 + [[300, 300]]
    layout = plan_shuffle_reads(combined, c, producer_bytes=producer_bytes,
                                skew_bytes=probe_heavy)
    assert layout is not None and layout_has_splits(layout)


def test_should_broadcast():
    c = conf(broadcast_threshold_bytes=32 * MB)
    assert should_broadcast(1 * MB, c)
    assert not should_broadcast(33 * MB, c)
    assert not should_broadcast(1, conf(broadcast=False))
    assert not should_broadcast(1, conf(enabled=False))


# ---------------------------------------------------------------------------
# Config resolution: settings > env > default
# ---------------------------------------------------------------------------


def test_config_defaults():
    c = AdaptiveConfig.from_settings({}, env={})
    assert c.enabled and c.coalesce and c.broadcast and c.skew
    assert c.target_partition_bytes == 64 * MB
    assert c.broadcast_threshold_bytes == 32 * MB
    assert c.skew_factor == 4.0


def test_config_env_overrides_and_settings_precedence():
    env = {"BALLISTA_ADAPTIVE_TARGET_PARTITION_BYTES": "1000",
           "BALLISTA_ADAPTIVE_SKEW_FACTOR": "8",
           "BALLISTA_ADAPTIVE_BROADCAST": "off"}
    c = AdaptiveConfig.from_settings({}, env=env)
    assert c.target_partition_bytes == 1000
    assert c.skew_factor == 8.0
    assert not c.broadcast_enabled
    # explicit settings beat env
    c = AdaptiveConfig.from_settings(
        {"adaptive.target_partition_bytes": "2000",
         "adaptive.broadcast": "on"}, env=env)
    assert c.target_partition_bytes == 2000
    assert c.broadcast_enabled


def test_config_per_rule_gates_and_validation():
    c = AdaptiveConfig.from_settings({"adaptive.enabled": "off"}, env={})
    assert not (c.coalesce_enabled or c.broadcast_enabled or c.skew_enabled)
    c = AdaptiveConfig.from_settings({"adaptive.coalesce": "off"}, env={})
    assert not c.coalesce_enabled and c.skew_enabled
    with pytest.raises(ValueError, match="target_partition_bytes"):
        AdaptiveConfig.from_settings(
            {"adaptive.target_partition_bytes": "lots"}, env={})
    with pytest.raises(ValueError, match="skew_factor"):
        AdaptiveConfig.from_settings({"adaptive.skew_factor": "0.5"}, env={})


# ---------------------------------------------------------------------------
# Wire contract: serde round-trips + proto drift guard
# ---------------------------------------------------------------------------


def test_proto_pb2_sync_guard():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "dev"))
    try:
        import check_proto_sync
    finally:
        sys.path.pop(0)
    assert check_proto_sync.check() == []


def test_shuffle_reader_serde_roundtrip():
    from ballista_tpu import serde
    from ballista_tpu.distributed.types import PartitionLocation
    from ballista_tpu.physical.shuffle import ShuffleReaderExec

    locs = [
        PartitionLocation("j", 1, p, "e", "h", 1, path=f"/x/{p}/{q}",
                          shuffle_output=q,
                          stats={"num_rows": 5, "num_bytes": 50,
                                 "shuffle_partition_bytes": [10, 40]})
        for p in range(2) for q in range(2)
    ]
    s = schema(("a", Int64))
    reader = ShuffleReaderExec(
        locs, s, read_partitions=[[(0, 1, 0, 1)], [(0, 1, 1, 2)],
                                  [(1, 2, 0, 0)]],
        hash_columns=("a",), original_partitions=2,
    )
    back = serde.physical_from_proto(serde.physical_to_proto(reader))
    assert back.read_partitions == reader.read_partitions
    assert back.hash_columns == ("a",)
    assert back.original_partitions == 2
    assert back.partition_locations[0].stats["shuffle_partition_bytes"] == \
        [10, 40]
    # groups: two producer-split reads of bucket 0, one whole bucket 1
    assert [len(g) for g in back._groups] == [1, 1, 2]


def test_join_adaptive_note_serde(tmp_path):
    from ballista_tpu import serde
    from ballista_tpu.physical.join import JoinExec
    from ballista_tpu.physical.operators import ScanExec

    p = tmp_path / "k.tbl"
    _write_tbl(p, [(1,), (2,)])
    s = schema(("k", Int64))

    def scan():
        return ScanExec("t", TblSource(str(p), s))

    j = JoinExec(scan(), scan(), [("k", "k")], "inner",
                 adaptive_note="broadcast build (test)")
    back = serde.physical_from_proto(serde.physical_to_proto(j))
    assert back.adaptive_note == "broadcast build (test)"
    assert "[adaptive: broadcast build (test)]" in back.display()
    # with_new_children preserves the annotation
    assert back.with_new_children(back.children()).adaptive_note == \
        back.adaptive_note


def test_shuffle_reader_reports_hash_partitioning():
    """Satellite fix: a reader over a hash-shuffled stage must report
    Partitioning("hash", n, cols), not ("unknown", n) — unless skew
    splits broke bucket integrity."""
    from ballista_tpu.distributed.types import PartitionLocation
    from ballista_tpu.physical.shuffle import ShuffleReaderExec

    s = schema(("a", Int64), ("b", Decimal(2)))
    locs = [PartitionLocation("j", 1, 0, "e", "h", 1, shuffle_output=q)
            for q in range(4)]
    reader = ShuffleReaderExec(locs, s, hash_columns=("a",))
    part = reader.output_partitioning()
    assert (part.kind, part.num_partitions, part.hash_columns) == \
        ("hash", 4, ("a",))
    # without the producer's hash exprs: unknown (the old behavior)
    assert ShuffleReaderExec(locs, s).output_partitioning().kind == "unknown"
    # coalesced whole buckets keep the hash property
    from ballista_tpu.physical.base import Partitioning

    coalesced = ShuffleReaderExec(locs, s, hash_columns=("a",),
                                  read_partitions=[[(0, 4, 0, 0)]])
    assert coalesced.output_partitioning() == Partitioning("hash", 1, ("a",))
    # producer-level splits break it
    split = ShuffleReaderExec(locs, s, hash_columns=("a",),
                              read_partitions=[[(0, 4, 0, 0)],
                                               [(3, 4, 0, 1)]])
    assert split.output_partitioning().kind == "unknown"


# ---------------------------------------------------------------------------
# Stage versions: superseded-task reports are dropped
# ---------------------------------------------------------------------------


def test_stage_version_supersedes_reports():
    from ballista_tpu.distributed.state import MemoryBackend, SchedulerState
    from ballista_tpu.distributed.types import (JobStatus, PartitionId,
                                                TaskStatus)

    st = SchedulerState(MemoryBackend())
    st.save_job_status("j1", JobStatus("queued"))
    st.save_stage_plan("j1", 1, b"x", 4, [])
    for p in range(4):
        st.save_task_status(TaskStatus(PartitionId("j1", 1, p)))
    st.enqueue_job("j1")
    assert st.stage_version("j1", 1) == 0
    v = st.update_stage_plan("j1", 1, num_partitions=2)
    assert v == 1 and st.stage_version("j1", 1) == 1
    # old rows dropped, 2 fresh pending rows
    tasks = st.get_task_statuses("j1", 1)
    assert len(tasks) == 2 and all(t.state is None for t in tasks)
    # a report from the superseded version is refused; current accepted
    stale = TaskStatus(PartitionId("j1", 1, 0), "completed",
                       executor_id="e", path="p", stats={}, stage_version=0)
    fresh = TaskStatus(PartitionId("j1", 1, 0), "completed",
                       executor_id="e", path="p", stats={}, stage_version=1)
    assert not st.accept_report_version(stale)
    assert st.accept_report_version(fresh)
    # a stranded v0 "running" row is reset + re-queued by a stale report
    drained = 0
    while st.next_task() is not None:
        drained += 1
    st.save_task_status(TaskStatus(PartitionId("j1", 1, 1), "running",
                                   executor_id="a", stage_version=0))
    assert not st.accept_report_version(
        TaskStatus(PartitionId("j1", 1, 1), "failed", error="x",
                   stage_version=0))
    row = next(t for t in st.get_task_statuses("j1", 1)
               if t.partition.partition_id == 1)
    assert row.state is None  # reset to pending
    # ...but a HEALTHY current-version running row is left alone
    st.save_task_status(TaskStatus(PartitionId("j1", 1, 1), "running",
                                   executor_id="b", stage_version=1))
    assert not st.accept_report_version(
        TaskStatus(PartitionId("j1", 1, 1), "failed", error="x",
                   stage_version=0))
    row = next(t for t in st.get_task_statuses("j1", 1)
               if t.partition.partition_id == 1)
    assert row.state == "running" and row.executor_id == "b"


# ---------------------------------------------------------------------------
# Standalone rewrites
# ---------------------------------------------------------------------------


def _write_tbl(path, rows):
    with open(path, "w") as fh:
        for r in rows:
            fh.write("|".join(str(x) for x in r) + "|\n")


@pytest.fixture(scope="module")
def join_data(tmp_path_factory):
    d = tmp_path_factory.mktemp("aqe")
    dim_dir = d / "dim"
    dim_dir.mkdir()
    # two fragments (standalone "producers"), heavy skew onto key 7
    for part in range(2):
        _write_tbl(dim_dir / f"{part}.tbl",
                   [(7 if i % 10 else i % 50, f"s{i % 6}")
                    for i in range(1500)])
    fact = d / "fact.tbl"
    _write_tbl(fact, [(i, i % 50, f"{(i % 9) + 0.5:.2f}")
                      for i in range(5000)])
    dim_s = schema(("dkey", Int64), ("seg", Utf8))
    fact_s = schema(("fid", Int64), ("fkey", Int64), ("v", Decimal(2)))
    return str(dim_dir), dim_s, str(fact), fact_s


JOIN_SQL = ("select seg, sum(v) as sv from fact, dim "
            "where fkey = dkey group by seg order by seg")


def _standalone_ctx(join_data, **settings):
    dim_dir, dim_s, fact, fact_s = join_data
    ctx = BallistaContext.standalone(
        **{"join.partitioned.threshold": "100", **settings})
    ctx.register_source("dim", TblSource(dim_dir, dim_s))
    ctx.register_source("fact", TblSource(fact, fact_s))
    return ctx


def test_standalone_join_demotion_and_determinism(join_data):
    on = _standalone_ctx(join_data).sql(JOIN_SQL).collect()
    off = _standalone_ctx(
        join_data, **{"adaptive.enabled": "off"}).sql(JOIN_SQL).collect()
    np.testing.assert_array_equal(on["seg"], off["seg"])
    np.testing.assert_allclose(on["sv"], off["sv"], rtol=1e-9)
    # the observed build side is tiny -> ANALYZE shows the demotion
    txt = _standalone_ctx(join_data).sql(
        "explain analyze " + JOIN_SQL).collect()
    plan = dict(zip(txt["plan_type"], txt["plan"]))["plan_with_metrics"]
    assert "[adaptive: broadcast build" in plan


def test_standalone_skew_split_and_determinism(join_data):
    aggressive = {"adaptive.broadcast_threshold_bytes": "1",
                  "adaptive.target_partition_bytes": "4000",
                  "adaptive.skew_factor": "2"}
    on = _standalone_ctx(join_data, **aggressive).sql(JOIN_SQL).collect()
    off = _standalone_ctx(
        join_data, **{"adaptive.enabled": "off"}).sql(JOIN_SQL).collect()
    np.testing.assert_array_equal(on["seg"], off["seg"])
    np.testing.assert_allclose(on["sv"], off["sv"], rtol=1e-9)
    txt = _standalone_ctx(join_data, **aggressive).sql(
        "explain analyze " + JOIN_SQL).collect()
    plan = dict(zip(txt["plan_type"], txt["plan"]))["plan_with_metrics"]
    assert "AdaptiveShuffleReadExec" in plan
    assert "split skewed partition" in plan


def test_standalone_lone_repartition_coalesce(join_data):
    """A user .repartition() outside any join coalesces (whole buckets
    only) and rows survive unchanged."""
    dim_dir, dim_s, _, _ = join_data
    ctx = BallistaContext.standalone(
        **{"adaptive.target_partition_bytes": str(64 * MB)})
    ctx.register_source("dim", TblSource(dim_dir, dim_s))
    df = ctx.table("dim").repartition(6, [col("seg")]) \
        .aggregate([col("seg")], [sum_(col("dkey")).alias("s")])
    got = df.collect().sort_values("seg").reset_index(drop=True)
    ctx_off = BallistaContext.standalone(**{"adaptive.enabled": "0"})
    ctx_off.register_source("dim", TblSource(dim_dir, dim_s))
    exp = ctx_off.table("dim").repartition(6, [col("seg")]) \
        .aggregate([col("seg")], [sum_(col("dkey")).alias("s")]) \
        .collect().sort_values("seg").reset_index(drop=True)
    np.testing.assert_array_equal(got["seg"], exp["seg"])
    np.testing.assert_array_equal(got["s"], exp["s"])


# ---------------------------------------------------------------------------
# Cluster e2e
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cluster():
    from ballista_tpu.distributed.executor import LocalCluster

    c = LocalCluster(num_executors=2, concurrent_tasks=2)
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def serial_cluster():
    """One executor, one slot: stages run one task at a time, so a
    completed build side reliably precedes any probe-side dispatch —
    the join-demotion window."""
    from ballista_tpu.distributed.executor import LocalCluster

    c = LocalCluster(num_executors=1, concurrent_tasks=1)
    yield c
    c.shutdown()


def _submit(port, df, settings):
    from ballista_tpu.distributed.client import (_fetch_result_frames,
                                                 submit_plan, wait_for_job)
    from ballista_tpu.execution import resolve_scalar_subqueries

    job = submit_plan("localhost", port,
                      resolve_scalar_subqueries(df.plan), settings)
    res = wait_for_job("localhost", port, job, timeout=120)
    return job, _fetch_result_frames(res)


def _task_counts(state, job):
    return {sid: len(state.get_task_statuses(job, sid))
            for sid in state.stage_ids(job)}


def test_cluster_coalesce_dispatches_fewer_tasks(cluster, tmp_path):
    """Acceptance: a small shuffle intermediate dispatches measurably
    fewer reader tasks than the static plan, with row-identical
    results; producers report the per-partition byte histogram."""
    p = tmp_path / "t.tbl"
    _write_tbl(p, [(i, f"{(i % 7) + 0.25:.2f}", f"k{i % 5}")
                   for i in range(1000)])
    src = TblSource(str(p), schema(("a", Int64), ("b", Decimal(2)),
                                   ("c", Utf8)))
    results = {}
    counts = {}
    for label, settings in (("on", {}), ("off", {"adaptive.enabled": "0"})):
        ctx = BallistaContext.remote("localhost", cluster.port, **settings)
        ctx.register_source("t", src)
        df = ctx.table("t").repartition(8, [col("c")]).aggregate(
            [col("c")], [sum_(col("b")).alias("s")])
        job, frame = _submit(cluster.port, df, ctx.settings)
        results[label] = frame.sort_values("c").reset_index(drop=True)
        counts[label] = _task_counts(cluster.state, job)
        if label == "on":
            # the shuffle producer reported its per-output histogram
            shuffle_sid = min(counts[label])
            t = cluster.state.get_task_statuses(job, shuffle_sid)[0]
            assert len(t.stats["shuffle_partition_bytes"]) == 8
            # the consumer stage was re-planned and versioned
            replanned = [sid for sid in cluster.state.stage_ids(job)
                         if cluster.state.get_stage_plan(job, sid).version]
            assert replanned, "no stage was adaptively re-planned"
    assert sum(counts["on"].values()) < sum(counts["off"].values())
    assert max(counts["off"].values()) == 8
    np.testing.assert_array_equal(results["on"]["c"], results["off"]["c"])
    np.testing.assert_allclose(results["on"]["s"], results["off"]["s"],
                               rtol=1e-9)


def _register_join_tables(ctx, tmp_path):
    dim = tmp_path / "dim.tbl"
    if not dim.exists():
        _write_tbl(dim, [(i, f"cat{i % 4}") for i in range(50)])
    fact = tmp_path / "fact.tbl"
    if not fact.exists():
        _write_tbl(fact, [(i, i % 50, f"{(i % 9) + 0.5:.2f}")
                          for i in range(5000)])
    ctx.register_source("dim", TblSource(
        str(dim), schema(("dkey", Int64), ("cat", Utf8))),
        primary_key="dkey")
    ctx.register_source("fact", TblSource(
        str(fact), schema(("fid", Int64), ("fkey", Int64),
                          ("v", Decimal(2)))))


def test_cluster_join_demotion(serial_cluster, tmp_path):
    """The filtered build side's observed bytes land under the
    broadcast threshold while the probe shuffle is still pending: the
    join demotes, the probe stage loses its shuffle spec, and results
    match the static plan."""
    sql = ("select cat, sum(v) as sv from fact, dim "
           "where fkey = dkey and fid < 30 group by cat order by cat")
    frames = {}
    for label, settings in (
        ("on", {"join.partitioned.threshold": "10"}),
        ("off", {"join.partitioned.threshold": "10",
                 "adaptive.enabled": "false"}),
    ):
        ctx = BallistaContext.remote("localhost", serial_cluster.port,
                                     **settings)
        _register_join_tables(ctx, tmp_path)
        job, frame = _submit(serial_cluster.port, ctx.sql(sql), ctx.settings)
        frames[label] = frame.sort_values("cat").reset_index(drop=True)
        if label == "on":
            state = serial_cluster.state
            # at least the join stage (and the unshuffled probe stage)
            # must have been re-planned
            versions = {sid: state.get_stage_plan(job, sid).version
                        for sid in state.stage_ids(job)}
            assert sum(1 for v in versions.values() if v > 0) >= 2, versions
            # the probe stage's shuffle spec was dropped
            specless = [sid for sid in state.stage_ids(job)
                        if versions[sid] > 0
                        and state.get_stage_plan(job, sid).shuffle_spec
                        is None]
            assert specless, versions
            # the demoted consumer keeps a producer-keyed fallback
            # layout for the probe dep (correct under either probe
            # format — see replanner._maybe_demote_join)
            probe_sid = specless[0]
            consumer = next(
                sid for sid in state.stage_ids(job)
                if (state.get_stage_plan(job, sid).reader_layouts or {})
                .get(probe_sid))
            layout = state.get_stage_plan(
                job, consumer).reader_layouts[probe_sid]
            assert all(len(ranges) == 1 and ranges[0][3] == ranges[0][2] + 1
                       for ranges in layout), layout
    np.testing.assert_array_equal(frames["on"]["cat"], frames["off"]["cat"])
    np.testing.assert_allclose(frames["on"]["sv"], frames["off"]["sv"],
                               rtol=1e-9)


def test_cluster_skew_split(cluster, tmp_path):
    """A hot hash bucket on the probe side splits into producer
    subranges (demotion gated off so the co-partitioned join
    survives)."""
    dim_dir = tmp_path / "dimskew"
    dim_dir.mkdir()
    for part in range(2):  # 2 scan partitions -> 2 shuffle producers
        _write_tbl(dim_dir / f"{part}.tbl",
                   [(7 if i % 10 else i % 50, f"s{i % 6}")
                    for i in range(1500)])
    fact = tmp_path / "factskew.tbl"
    _write_tbl(fact, [(i, i % 50, f"{(i % 9) + 0.5:.2f}")
                      for i in range(5000)])
    dim_s = schema(("dkey", Int64), ("seg", Utf8))
    fact_s = schema(("fid", Int64), ("fkey", Int64), ("v", Decimal(2)))
    sql = ("select seg, sum(v) as sv from fact, dim "
           "where fkey = dkey group by seg order by seg")
    frames = {}
    for label, settings in (
        ("on", {"join.partitioned.threshold": "100",
                "adaptive.broadcast": "off",
                "adaptive.target_partition_bytes": "4000",
                "adaptive.skew_factor": "2"}),
        ("off", {"join.partitioned.threshold": "100",
                 "adaptive.enabled": "off"}),
    ):
        ctx = BallistaContext.remote("localhost", cluster.port, **settings)
        ctx.register_source("dim", TblSource(str(dim_dir), dim_s))
        ctx.register_source("fact", TblSource(str(fact), fact_s))
        job, frame = _submit(cluster.port, ctx.sql(sql), ctx.settings)
        frames[label] = frame.sort_values("seg").reset_index(drop=True)
        if label == "on":
            state = cluster.state
            layouts = [state.get_stage_plan(job, sid).reader_layouts
                       for sid in state.stage_ids(job)
                       if state.get_stage_plan(job, sid).reader_layouts]
            assert layouts, "no adaptive reader layout was recorded"
            has_split = any(
                r[3] != 0
                for layout in layouts for dep in layout.values()
                for ranges in dep for r in ranges
            )
            assert has_split, layouts
    np.testing.assert_array_equal(frames["on"]["seg"], frames["off"]["seg"])
    np.testing.assert_allclose(frames["on"]["sv"], frames["off"]["sv"],
                               rtol=1e-9)


# ---------------------------------------------------------------------------
# Determinism: AQE on vs off over the tier-1 TPC-H query suite
# ---------------------------------------------------------------------------

TPCH_QUERIES = ["q1", "q3", "q5", "q12", "q14", "q16", "q17", "q18", "q19"]
QDIR = os.path.join(os.path.dirname(__file__), "..", "benchmarks", "tpch",
                    "queries")


@pytest.fixture(scope="module")
def tpch_pair(tmp_path_factory):
    from benchmarks.tpch import datagen
    from benchmarks.tpch.schema_def import register_tpch

    data_dir = str(tmp_path_factory.mktemp("aqe_tpch"))
    datagen.generate(data_dir, scale=0.002, num_parts=2)
    # aggressive thresholds so the rules actually fire at toy scale;
    # identical planner settings on both sides — only AQE differs
    force = {"join.partitioned.threshold": "50",
             "adaptive.target_partition_bytes": "20000",
             "adaptive.skew_factor": "2"}
    on = BallistaContext.standalone(**force)
    off = BallistaContext.standalone(**{**force, "adaptive.enabled": "off"})
    register_tpch(on, data_dir, "tbl")
    register_tpch(off, data_dir, "tbl")
    return on, off


@pytest.mark.parametrize("qname", TPCH_QUERIES)
def test_tpch_rows_identical_with_aqe(tpch_pair, qname):
    on, off = tpch_pair
    sql = open(os.path.join(QDIR, f"{qname}.sql")).read()
    got = on.sql(sql).collect()
    exp = off.sql(sql).collect()
    assert list(got.columns) == list(exp.columns)
    assert len(got) == len(exp), f"{qname}: {len(got)} vs {len(exp)} rows"
    for c in exp.columns:
        g, e = got[c], exp[c]
        if e.dtype.kind in "fc":
            np.testing.assert_allclose(
                g.astype(float), e.astype(float), rtol=1e-9, atol=1e-9,
                err_msg=f"{qname}.{c}")
        else:
            np.testing.assert_array_equal(g.to_numpy(), e.to_numpy(),
                                          err_msg=f"{qname}.{c}")
