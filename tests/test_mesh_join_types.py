"""MeshJoinExec join-type parity: every SPMD join type must match the
host JoinExec oracle (same inputs, same semantics — physical/join.py),
row-for-row after sorting.

Round 2 shipped inner-only mesh joins; round 3 adds left/semi/anti/full
(co-partitioning makes unmatched-row detection local to each device) and
the scheduler fuses every partitioned join type.
"""

import numpy as np
import pandas as pd
import pytest

from ballista_tpu import Int64, Utf8, schema
from ballista_tpu.columnar import ColumnBatch
from ballista_tpu.io import MemTableSource
from ballista_tpu.physical.join import JoinExec
from ballista_tpu.physical.mesh_agg import MeshJoinExec
from ballista_tpu.physical.operators import ScanExec


def _collect(plan):
    frames = []
    for p in range(plan.output_partitioning().num_partitions):
        for b in plan.execute(p):
            frames.append(b.to_pandas())
    out = pd.concat(frames, ignore_index=True)
    out = out.sort_values(list(out.columns)).reset_index(drop=True)
    # normalize missing-value representation: concat can infer StringDtype
    # (NaN missing) on one side and object (None) on the other
    return out.astype(object).where(pd.notna(out), None)


def _sources(with_nulls=False):
    """Build/probe tables with duplicate keys, misses on both sides, and
    (optionally) NULL join keys."""
    rng = np.random.default_rng(3)
    bs = schema(("bk", Int64), ("bv", Int64))
    ps = schema(("pk_", Int64), ("pv", Int64))
    bk = rng.integers(0, 12, 40)
    pk = rng.integers(5, 20, 90)  # keys 0-4 build-only, 12-19 probe-only
    build_parts, probe_parts = [], []
    for c in np.array_split(np.arange(40), 3):
        b = ColumnBatch.from_pydict(
            bs, {"bk": bk[c], "bv": c * 10})
        if with_nulls:  # every 7th build key NULL
            import jax.numpy as jnp
            col = b.columns[0]
            validity = np.zeros(b.capacity, bool)
            validity[: len(c)] = (c % 7) != 0
            b.columns = (type(col)(col.values, col.dtype,
                                   jnp.asarray(validity),
                                   col.dictionary),) + b.columns[1:]
        build_parts.append([b])
    for c in np.array_split(np.arange(90), 4):
        probe_parts.append([ColumnBatch.from_pydict(
            ps, {"pk_": pk[c], "pv": c})])
    return (ScanExec("b", MemTableSource(bs, build_parts)),
            ScanExec("p", MemTableSource(ps, probe_parts)))


@pytest.mark.parametrize("how", ["inner", "left", "semi", "anti", "full"])
def test_mesh_join_matches_host(eight_devices, how):
    build, probe = _sources()
    host = JoinExec(build, probe, [("bk", "pk_")], how)
    mesh = MeshJoinExec(build, probe, [("bk", "pk_")], how, 8)
    got = _collect(mesh)
    exp = _collect(host)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


@pytest.mark.parametrize("how", ["inner", "left", "full"])
def test_mesh_join_null_keys_match_host(eight_devices, how):
    """NULL join keys never match but outer semantics still emit them."""
    build, probe = _sources(with_nulls=True)
    host = JoinExec(build, probe, [("bk", "pk_")], how)
    mesh = MeshJoinExec(build, probe, [("bk", "pk_")], how, 8)
    pd.testing.assert_frame_equal(_collect(mesh), _collect(host),
                                  check_dtype=False)


def _utf8_sources():
    """utf8 join keys with DISJOINT per-partition dictionaries (forces
    the probe->build remap path) + a second Int64 key column for the
    multi-key codec path."""
    bs = schema(("bk", Utf8), ("b2", Int64), ("bv", Int64))
    ps = schema(("pk_", Utf8), ("p2", Int64), ("pv", Int64))
    rng = np.random.default_rng(11)
    build_parts = [
        [ColumnBatch.from_pydict(bs, {
            "bk": [f"k{i % 9}" for i in c],
            "b2": (c % 3).tolist(),
            "bv": (c * 7).tolist()})]
        for c in np.array_split(np.arange(30), 2)
    ]
    probe_parts = [
        [ColumnBatch.from_pydict(ps, {
            "pk_": [f"k{i % 14}" for i in c],  # k9..k13 never match
            "p2": (c % 4).tolist(),
            "pv": c.tolist()})]
        for c in np.array_split(np.arange(80), 3)
    ]
    return (ScanExec("b", MemTableSource(bs, build_parts)),
            ScanExec("p", MemTableSource(ps, probe_parts)))


@pytest.mark.parametrize("how", ["inner", "left", "semi", "anti", "full"])
def test_mesh_join_utf8_remap_matches_host(eight_devices, how):
    """utf8 keys: probe codes must be remapped into the build dictionary
    space inside the SPMD program; misses count as unmatched."""
    build, probe = _utf8_sources()
    host = JoinExec(build, probe, [("bk", "pk_")], how)
    mesh = MeshJoinExec(build, probe, [("bk", "pk_")], how, 8)
    pd.testing.assert_frame_equal(_collect(mesh), _collect(host),
                                  check_dtype=False)


@pytest.mark.parametrize("how", ["inner", "left", "full"])
def test_mesh_join_composite_codec_matches_host(eight_devices, how):
    """Two-column (utf8, int64) keys exercise the exact rank-codec build
    and probe inside the mesh program."""
    build, probe = _utf8_sources()
    on = [("bk", "pk_"), ("b2", "p2")]
    host = JoinExec(build, probe, on, how)
    mesh = MeshJoinExec(build, probe, on, how, 8)
    pd.testing.assert_frame_equal(_collect(mesh), _collect(host),
                                  check_dtype=False)


@pytest.mark.parametrize("build_nulls", [True, False])
def test_mesh_null_aware_anti_matches_host(eight_devices, build_nulls):
    """SQL NOT IN semantics on the mesh: a null key anywhere in the
    build side (any device) empties the result; probe null keys are
    always dropped. Must match the host null_aware anti join."""
    build, probe = _sources(with_nulls=build_nulls)
    host = JoinExec(build, probe, [("bk", "pk_")], "anti", null_aware=True)
    mesh = MeshJoinExec(build, probe, [("bk", "pk_")], "anti", 8,
                        null_aware=True)
    got, exp = _collect(mesh), _collect(host)
    if build_nulls:
        assert len(exp) == 0  # NULL in the subquery: predicate never true
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_scheduler_fuses_partitioned_left_join(eight_devices):
    """The fusion pass now fuses every partitioned join type, not just
    inner (scheduler.replace_join)."""
    from ballista_tpu import col
    from ballista_tpu.distributed.planner import DistributedPlanner
    from ballista_tpu.distributed.scheduler import _fuse_mesh_stages
    from ballista_tpu.logical import LogicalPlanBuilder
    from ballista_tpu.physical.planner import (
        PlannerOptions, create_physical_plan,
    )

    bs = schema(("bk", Int64), ("bv", Int64))
    ps = schema(("pk_", Int64), ("pv", Int64))
    bsrc = MemTableSource(bs, [[ColumnBatch.from_pydict(
        bs, {"bk": list(range(30)), "bv": list(range(30))})]])
    psrc = MemTableSource(ps, [[ColumnBatch.from_pydict(
        ps, {"pk_": list(range(50)), "pv": list(range(50))})]])
    plan = (
        LogicalPlanBuilder.scan("p", psrc)
        .join(LogicalPlanBuilder.scan("b", bsrc),
              [("pk_", "bk")], how="left")
        .build()
    )
    phys = create_physical_plan(
        plan, PlannerOptions(join_partition_threshold=1, join_partitions=8))
    stages = DistributedPlanner().plan_query_stages("j1", phys)
    fused = _fuse_mesh_stages(stages, 8)
    found = []

    def walk(n):
        if isinstance(n, MeshJoinExec):
            found.append(n)
        for c in n.children():
            walk(c)

    for s in fused:
        walk(s.child)
    assert found and found[0].how == "left", [s.child.pretty() for s in fused]
