"""TPC-H at SF=0.2 (~1.2M lineitem rows) through BOTH the standalone
engine and the distributed LocalCluster, asserted against pandas oracles.

Opt-in (``pytest -m sf02``): the CI-scale suite (test_tpch.py, SF=0.002)
never exercises capacity-overflow/retry paths or the distributed shuffle
under realistic data sizes — this one does. Round-1 lesson: bugs appear
only at scale (q7's OR-collapse showed up first at SF0.05).
"""

import os

import numpy as np
import pandas as pd
import pytest

from benchmarks.tpch import datagen, oracle
from benchmarks.tpch.schema_def import register_tpch

QUERIES = [f"q{i}" for i in range(1, 23)]
QDIR = os.path.join(os.path.dirname(__file__), "..", "benchmarks", "tpch",
                    "queries")

pytestmark = pytest.mark.sf02


@pytest.fixture(scope="session")
def sf02_data(tmp_path_factory):
    # reuse the bench dataset when present (same generator + seed)
    prebuilt = os.path.join(os.path.dirname(__file__), "..", "bench_data",
                            "sf02")
    if os.path.exists(os.path.join(prebuilt, "lineitem")):
        data_dir = prebuilt
    else:
        data_dir = str(tmp_path_factory.mktemp("tpch_sf02"))
        datagen.generate(data_dir, scale=0.2, num_parts=2)
    return data_dir, oracle.load_tables(data_dir)


@pytest.fixture(scope="session")
def sf02_standalone(sf02_data):
    from ballista_tpu.client import BallistaContext

    data_dir, tables = sf02_data
    ctx = BallistaContext.standalone()
    register_tpch(ctx, data_dir, "tbl", cached=True)
    return ctx, tables


@pytest.fixture(scope="session")
def sf02_cluster(sf02_data):
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.distributed.executor import LocalCluster

    data_dir, tables = sf02_data
    cluster = LocalCluster(num_executors=2, concurrent_tasks=2)
    ctx = BallistaContext.remote("localhost", cluster.port)
    register_tpch(ctx, data_dir, "tbl")
    yield ctx, tables
    cluster.shutdown()


def _normalize(df: pd.DataFrame) -> pd.DataFrame:
    out = df.copy()
    for c in out.columns:
        if out[c].dtype.kind == "M":
            out[c] = out[c].values.astype("datetime64[D]")
    return out.reset_index(drop=True)


def _assert_matches(got, exp, qname):
    got, exp = _normalize(got), _normalize(exp)
    assert list(got.columns) == list(exp.columns), (got.columns, exp.columns)
    assert len(got) == len(exp), f"{qname}: {len(got)} rows vs {len(exp)}"
    for c in exp.columns:
        g, e = got[c], exp[c]
        if e.dtype.kind in "fc":
            np.testing.assert_allclose(
                g.astype(float), e.astype(float), rtol=1e-6, atol=1e-6,
                err_msg=f"{qname}.{c}",
            )
        else:
            np.testing.assert_array_equal(
                g.to_numpy(), e.to_numpy(), err_msg=f"{qname}.{c}"
            )


@pytest.mark.parametrize("qname", QUERIES)
def test_sf02_standalone(sf02_standalone, qname):
    ctx, tables = sf02_standalone
    sql = open(os.path.join(QDIR, f"{qname}.sql")).read()
    _assert_matches(ctx.sql(sql).collect(), oracle.ORACLES[qname](tables),
                    qname)


@pytest.mark.parametrize("qname", QUERIES)
def test_sf02_cluster(sf02_cluster, qname):
    ctx, tables = sf02_cluster
    sql = open(os.path.join(QDIR, f"{qname}.sql")).read()
    _assert_matches(ctx.sql(sql).collect(), oracle.ORACLES[qname](tables),
                    qname)
