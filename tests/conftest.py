"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is unavailable in CI, so all sharding/collective
paths are exercised on a virtual 8-device CPU topology, mirroring how the
reference tests multi-node behavior without a cluster (reference:
rust/scheduler/src/lib.rs:444-491 tests gRPC services via direct calls).
"""

import os

# Force CPU: the ambient environment points JAX at the (slow, single-chip)
# axon TPU tunnel; tests want the fast virtual 8-device CPU topology.
# NOTE: the interpreter's sitecustomize imports jax at startup with
# JAX_PLATFORMS=axon already read, so the env var alone is too late —
# jax.config.update below is what actually flips the platform.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs[:8]
