"""Subprocess spawning for process-level tests, with pipe draining.

Child processes (scheduler/executor binaries, SPMD workers) can emit
arbitrarily much output — XLA warning spam alone can exceed the 64 KB
OS pipe buffer. A child that blocks on a full pipe write never answers
RPCs again and the test times out far from the cause, so every spawned
process gets a daemon reader thread that continuously drains stdout
into memory; tests wait on startup lines through `wait_for` instead of
reading the pipe directly.
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional


class DrainedProc:
    """A Popen wrapper whose stdout is drained on a background thread."""

    def __init__(self, popen: subprocess.Popen):
        self.popen = popen
        self._lines: List[str] = []
        self._cond = threading.Condition()
        self._eof = False
        t = threading.Thread(target=self._drain, daemon=True)
        t.start()

    def _drain(self) -> None:
        for line in self.popen.stdout:
            with self._cond:
                self._lines.append(line)
                self._cond.notify_all()
        with self._cond:
            self._eof = True
            self._cond.notify_all()

    def wait_for(self, pred: Callable[[str], bool],
                 timeout: float = 90.0) -> str:
        """Block until a drained line satisfies ``pred``; returns it.

        Raises AssertionError with the full captured output on timeout
        or child exit, so failures point at the child's real error."""
        deadline = time.time() + timeout
        seen = 0
        with self._cond:
            while True:
                while seen < len(self._lines):
                    if pred(self._lines[seen]):
                        return self._lines[seen]
                    seen += 1
                if self._eof:
                    raise AssertionError(
                        "process exited before expected output:\n"
                        + self.text[-4000:])
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise AssertionError(
                        "timeout waiting for expected output:\n"
                        + self.text[-4000:])
                self._cond.wait(min(remaining, 1.0))

    def wait_exit(self, timeout: float = 180.0) -> int:
        """Wait for process exit (output keeps draining); returns rc."""
        deadline = time.time() + timeout
        rc = self.popen.wait(timeout=timeout)
        with self._cond:
            # EOF may lag exit if a descendant inherited the pipe; honor
            # the caller's deadline rather than waiting forever
            while not self._eof and time.time() < deadline:
                self._cond.wait(1.0)
        return rc

    @property
    def text(self) -> str:
        with self._cond:
            return "".join(self._lines)

    # pass-throughs used by test teardown
    def poll(self):
        return self.popen.poll()

    def send_signal(self, sig):
        return self.popen.send_signal(sig)

    def wait(self, timeout=None):
        return self.popen.wait(timeout=timeout)

    def kill(self):
        return self.popen.kill()


def wait_healthz(port: int, timeout: float = 30.0,
                 host: str = "127.0.0.1") -> dict:
    """Poll the health plane's ``/healthz`` until it answers ``ok``
    (returns the parsed body). Replaces fixed sleeps in cluster test
    setup: the endpoint answers the moment the process can serve, so
    startup waits cost milliseconds instead of a worst-case sleep, and
    a dead process fails fast with the last error."""
    import json
    import urllib.error
    import urllib.request

    deadline = time.time() + timeout
    last_err: "Exception | None" = None
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                    f"http://{host}:{port}/healthz", timeout=2) as resp:
                body = json.loads(resp.read().decode())
                if body.get("status") == "ok":
                    return body
                last_err = AssertionError(f"unexpected body: {body}")
        except (urllib.error.URLError, OSError, ValueError) as e:
            last_err = e
        time.sleep(0.05)
    raise AssertionError(
        f"/healthz on port {port} not ready after {timeout}s: {last_err}")


def http_get(port: int, path: str, timeout: float = 5.0,
             host: str = "127.0.0.1") -> str:
    """One GET against a health plane endpoint; returns the body text."""
    import urllib.request

    with urllib.request.urlopen(
            f"http://{host}:{port}{path}", timeout=timeout) as resp:
        return resp.read().decode()


def spawn_module(args, env) -> DrainedProc:
    """``python -m <args>`` with stdout+stderr drained."""
    return DrainedProc(subprocess.Popen(
        [sys.executable, "-m"] + args, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    ))


def spawn_script(argv, env) -> DrainedProc:
    """``python -c <script> ...`` (or any argv after python) drained."""
    return DrainedProc(subprocess.Popen(
        [sys.executable] + argv, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    ))
