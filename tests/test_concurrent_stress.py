"""Concurrent-executor stress: REAL threaded poll loops racing speculation,
completion, failure recovery and lease bookkeeping against one scheduler.

The recovery suite drives executors manually single-threaded; this test
runs 4 executors x 2 worker threads against a live gRPC scheduler with:
- several jobs submitted concurrently from client threads,
- one executor killed mid-flight WITH its shuffle files deleted (the
  ShuffleFetchError re-queue path must rebuild lost producer output),
- one straggling executor (injected per-task latency) so duplicate /
  speculative completions race the fast executors' reports.

Exactly-once EFFECT is asserted through results: every job's output must
match the oracle exactly (duplicate task completions or corrupted shuffle
files would double-count or crash). Reference contrast: the reference
serializes this state machine behind one global lock and fails jobs on
any task failure (rust/scheduler/src/state/mod.rs:182-260, 342-346).
"""

import threading
import time

import numpy as np
import pytest

from ballista_tpu import schema, Int64, Utf8
from ballista_tpu.client import BallistaContext
from ballista_tpu.distributed.executor import LocalCluster
from ballista_tpu.io import TblSource


N_ROWS = 4000
N_PARTS = 8
N_GROUPS = 13


@pytest.fixture()
def big_source(tmp_path):
    d = tmp_path / "t"
    d.mkdir()
    rng = np.random.default_rng(3)
    keys = rng.integers(0, N_GROUPS, N_ROWS)
    vals = rng.integers(0, 1000, N_ROWS)
    per = N_ROWS // N_PARTS
    for p in range(N_PARTS):
        lines = [f"{vals[i]}|g{keys[i]}|"
                 for i in range(p * per, (p + 1) * per)]
        (d / f"part{p}.tbl").write_text("\n".join(lines) + "\n")
    src = TblSource(str(d), schema(("a", Int64), ("c", Utf8)))
    exp = {}
    for k, v in zip(keys, vals):
        e = exp.setdefault(f"g{k}", [0, 0])
        e[0] += int(v)
        e[1] += 1
    return src, exp


def _check(got, exp):
    assert len(got) == len(exp), (len(got), len(exp))
    for _, row in got.iterrows():
        s, n = exp[row["c"]]
        assert int(row["s"]) == s, row["c"]
        assert int(row["n"]) == n, row["c"]


def test_concurrent_executors_with_kill_and_straggler(big_source):
    src, exp = big_source
    cluster = LocalCluster(num_executors=4, concurrent_tasks=2)
    try:
        # straggler: executor 0 sleeps before every task, so its
        # completions race the others' speculative re-runs
        slow = cluster.executors[0]
        orig = slow.execute_partition

        def slow_execute(pid, plan, shuffle=None):
            time.sleep(0.4)
            return orig(pid, plan, shuffle)

        slow.execute_partition = slow_execute

        sql = ("select c, sum(a) as s, count(*) as n from t "
               "group by c order by c")
        results = {}
        errors = []

        def run_job(i):
            try:
                ctx = BallistaContext.remote(
                    "localhost", cluster.port,
                    **{"shuffle.partitions": "4"})
                ctx.register_source("t", src)
                results[i] = ctx.sql(sql).collect()
            except Exception as e:  # noqa: BLE001 - assert at the end
                errors.append((i, e))

        threads = [threading.Thread(target=run_job, args=(i,))
                   for i in range(5)]
        for t in threads:
            t.start()

        # mid-flight: kill an executor AND delete its shuffle output so
        # consumers hit ShuffleFetchError and the scheduler re-queues the
        # lost producers on the survivors
        time.sleep(0.5)
        victim = cluster.executors[1]
        victim.stop()
        import shutil

        shutil.rmtree(victim.config.work_dir, ignore_errors=True)

        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "job thread wedged"
        assert not errors, errors
        assert len(results) == 5
        for i in range(5):
            _check(results[i].sort_values("c").reset_index(drop=True), exp)
    finally:
        cluster.shutdown()


def test_many_small_jobs_no_cross_talk(big_source, tmp_path):
    """Two different tables queried concurrently: shuffle files from
    interleaved jobs on shared executors must never mix."""
    src, exp = big_source
    d2 = tmp_path / "u"
    d2.mkdir()
    for p in range(4):
        lines = [f"{i}|h{i % 5}|" for i in range(p, 400, 4)]
        (d2 / f"part{p}.tbl").write_text("\n".join(lines) + "\n")
    src2 = TblSource(str(d2), schema(("a", Int64), ("c", Utf8)))
    exp2 = {}
    for i in range(400):
        e = exp2.setdefault(f"h{i % 5}", [0, 0])
        e[0] += i
        e[1] += 1

    cluster = LocalCluster(num_executors=4, concurrent_tasks=2)
    try:
        out = {}

        def job(i):
            ctx = BallistaContext.remote("localhost", cluster.port,
                                         **{"shuffle.partitions": "3"})
            if i % 2 == 0:
                ctx.register_source("t", src)
                out[i] = ("t", ctx.sql(
                    "select c, sum(a) as s, count(*) as n from t group by c"
                ).collect())
            else:
                ctx.register_source("u", src2)
                out[i] = ("u", ctx.sql(
                    "select c, sum(a) as s, count(*) as n from u group by c"
                ).collect())

        threads = [threading.Thread(target=job, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive()
        assert len(out) == 6
        for i, (tag, got) in out.items():
            _check(got.sort_values("c").reset_index(drop=True),
                   exp if tag == "t" else exp2)
    finally:
        cluster.shutdown()
