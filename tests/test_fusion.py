"""Whole-stage fusion (physical/fusion.py): determinism, plan shape,
re-plan cache reuse, the distinct-count kernel, AOT export/load, and the
program-count regression gate.

The fusion pass reorders NOTHING — TPC-H results must be byte-identical
with ``BALLISTA_FUSION`` ON vs OFF, across the adaptive pass (default
on) and with the shape-bucket ladder on or off.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from ballista_tpu import Int64, Utf8, col, schema

QDIR = os.path.join(os.path.dirname(__file__), "..", "benchmarks", "tpch",
                    "queries")
DEV = os.path.join(os.path.dirname(__file__), "..", "dev")


@pytest.fixture(scope="module")
def tpch_dir(tmp_path_factory):
    from benchmarks.tpch import datagen

    d = str(tmp_path_factory.mktemp("fusion_tpch"))
    datagen.generate(d, scale=0.002, num_parts=2)
    return d


@pytest.fixture(autouse=True)
def _fusion_env(monkeypatch):
    """Tests toggle BALLISTA_FUSION (some via direct os.environ writes
    inside helpers); restore the process default afterwards either
    way."""
    prev = os.environ.get("BALLISTA_FUSION")
    yield
    monkeypatch.undo()
    if prev is None:
        os.environ.pop("BALLISTA_FUSION", None)
    else:
        os.environ["BALLISTA_FUSION"] = prev


def _run_tpch(data_dir, qname, fusion: str):
    from ballista_tpu.client import BallistaContext
    from benchmarks.tpch.schema_def import register_tpch

    os.environ["BALLISTA_FUSION"] = fusion
    ctx = BallistaContext.standalone()
    register_tpch(ctx, data_dir, "tbl")
    sql = open(os.path.join(QDIR, f"{qname}.sql")).read()
    df = ctx.sql(sql)
    out = df.collect()
    return out, df._phys


def _assert_byte_identical(a, b, tag):
    assert list(a.columns) == list(b.columns), tag
    assert len(a) == len(b), tag
    for c in a.columns:
        ga, gb = a[c].to_numpy(), b[c].to_numpy()
        assert ga.dtype == gb.dtype, f"{tag}.{c}: {ga.dtype} vs {gb.dtype}"
        if ga.dtype.kind in "fc":  # byte-identical, not merely close
            assert ga.tobytes() == gb.tobytes(), f"{tag}.{c}"
        else:
            np.testing.assert_array_equal(ga, gb, err_msg=f"{tag}.{c}")


def _count_type(phys, cls) -> int:
    n = int(isinstance(phys, cls))
    return n + sum(_count_type(c, cls) for c in phys.children())


# ---------------------------------------------------------------------------
# determinism: fusion ON vs OFF, byte-identical (adaptive pass included
# — it is on by default and q5/q12 exercise its join rules)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("qname", ["q1", "q5", "q12", "q16"])
def test_determinism_fusion_on_off(tpch_dir, monkeypatch, qname):
    monkeypatch.setenv("BALLISTA_FUSION", "0")
    base, _ = _run_tpch(tpch_dir, qname, "0")
    got, phys = _run_tpch(tpch_dir, qname, "on")
    _assert_byte_identical(base, got, qname)


def test_determinism_buckets_off(tpch_dir, monkeypatch):
    """Fusion must stay byte-identical when the shape-bucket ladder is
    disabled (exact power-of-two capacities)."""
    from ballista_tpu.compile import reconfigure

    monkeypatch.setenv("BALLISTA_SHAPE_BUCKETS", "off")
    reconfigure()
    try:
        base, _ = _run_tpch(tpch_dir, "q1", "0")
        got, _ = _run_tpch(tpch_dir, "q1", "on")
        _assert_byte_identical(base, got, "q1[buckets=off]")
    finally:
        monkeypatch.undo()
        reconfigure()


# ---------------------------------------------------------------------------
# plan shape: fused operators present, escape hatch works, EXPLAIN
# renders fusion groups
# ---------------------------------------------------------------------------


def test_fused_operators_in_plans(tpch_dir):
    from ballista_tpu.physical.fusion import (FusedDistinctCountExec,
                                              FusedStageExec)

    _, p1 = _run_tpch(tpch_dir, "q1", "on")
    assert _count_type(p1, FusedStageExec) >= 1, p1.pretty()
    _, p16 = _run_tpch(tpch_dir, "q16", "on")
    assert _count_type(p16, FusedDistinctCountExec) == 1, p16.pretty()


def test_fusion_escape_hatch(tpch_dir):
    from ballista_tpu.physical.fusion import (FusedDistinctCountExec,
                                              FusedStageExec)

    _, p1 = _run_tpch(tpch_dir, "q1", "0")
    assert _count_type(p1, FusedStageExec) == 0
    _, p16 = _run_tpch(tpch_dir, "q16", "0")
    assert _count_type(p16, FusedDistinctCountExec) == 0


def test_probe_chain_fused_into_join(tpch_dir):
    from ballista_tpu.physical.join import JoinExec

    _, p5 = _run_tpch(tpch_dir, "q5", "on")

    def any_fused_probe(node):
        if isinstance(node, JoinExec) and node.probe_chain:
            return True
        return any(any_fused_probe(c) for c in node.children())

    assert any_fused_probe(p5), p5.pretty()


def test_explain_renders_fusion_groups(tpch_dir, monkeypatch):
    from ballista_tpu.client import BallistaContext
    from benchmarks.tpch.schema_def import register_tpch

    monkeypatch.setenv("BALLISTA_FUSION", "on")
    ctx = BallistaContext.standalone()
    register_tpch(ctx, tpch_dir, "tbl")
    sql = open(os.path.join(QDIR, "q1.sql")).read().rstrip().rstrip(";")
    out = ctx.sql("explain " + sql).collect()
    text = out[out.plan_type == "physical_plan"].plan.iloc[0]
    assert "[fused stage" in text, text
    assert "[fused]" in text, text  # absorbed operators still rendered


def test_explain_analyze_fused_stage_metrics(tpch_dir, monkeypatch):
    """ANALYZE runs the fused plan and the fused stage line carries the
    compile/execute split."""
    from ballista_tpu.client import BallistaContext
    from benchmarks.tpch.schema_def import register_tpch

    monkeypatch.setenv("BALLISTA_FUSION", "on")
    ctx = BallistaContext.standalone()
    register_tpch(ctx, tpch_dir, "tbl")
    sql = open(os.path.join(QDIR, "q1.sql")).read().rstrip().rstrip(";")
    out = ctx.sql("explain analyze " + sql).collect()
    text = out[out.plan_type == "plan_with_metrics"].plan.iloc[0]
    stage_line = next(l for l in text.splitlines() if "[fused stage" in l)
    assert "elapsed_compute" in stage_line, text
    assert "output_rows" in stage_line, text


# ---------------------------------------------------------------------------
# re-plan: fresh operator instances re-fuse onto the same governed
# entries — zero new compiles (the adaptive-execution contract)
# ---------------------------------------------------------------------------


def _compile_requests() -> int:
    from ballista_tpu.compile import compile_stats

    st = compile_stats()
    return int(st["backend_compiles"]) + int(st["persistent_cache_hits"])


def test_replan_of_fused_plan_zero_new_compiles(tpch_dir, monkeypatch):
    from ballista_tpu.client import BallistaContext
    from benchmarks.tpch.schema_def import register_tpch

    monkeypatch.setenv("BALLISTA_FUSION", "on")
    ctx = BallistaContext.standalone()
    register_tpch(ctx, tpch_dir, "tbl")
    sql = open(os.path.join(QDIR, "q1.sql")).read()
    first = ctx.sql(sql).collect()
    # fresh DataFrame -> plan_logical + fuse_plan run again -> ALL-NEW
    # fused operator instances (same value signatures)
    ctx._plan_cache.clear()
    before = _compile_requests()
    second = ctx.sql(sql).collect()
    assert _compile_requests() == before, (
        "re-planned fused query issued new compile requests; fused "
        "signatures must reuse governed entries")
    assert first.equals(second)


# ---------------------------------------------------------------------------
# the distinct-count kernel
# ---------------------------------------------------------------------------


def test_grouped_distinct_count_kernel():
    import jax.numpy as jnp

    from ballista_tpu.kernels.aggregate import grouped_distinct_count

    rng = np.random.RandomState(3)
    n = 512
    g = rng.randint(0, 7, n).astype(np.int64)
    x = rng.randint(0, 23, n).astype(np.int64)
    live = rng.rand(n) > 0.2
    xvalid = rng.rand(n) > 0.3
    res = grouped_distinct_count(
        [jnp.asarray(g)], jnp.asarray(live), jnp.asarray(x), 16,
        distinct_validity=jnp.asarray(xvalid))
    got = {}
    order = np.asarray(res.rep_indices)
    counts = np.asarray(res.aggregates[0])
    valid = np.asarray(res.group_valid)
    for i in range(16):
        if valid[i]:
            got[g[order[i]]] = counts[i]
    exp = {}
    for gv in np.unique(g[live]):
        m = live & (g == gv)
        exp[gv] = len(np.unique(x[m & xvalid]))
    assert got == exp
    assert int(res.num_groups) == len(exp)


def test_distinct_single_partition_drops_dedup():
    """With a single input partition the (g, x) dedup partial is pure
    overhead — the fused stage must absorb the dedup's own scan chain
    instead."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.physical.fusion import FusedDistinctCountExec
    from ballista_tpu.physical.aggregate import HashAggregateExec

    os.environ["BALLISTA_FUSION"] = "on"
    ctx = BallistaContext.standalone()
    n = 400
    rng = np.random.RandomState(11)
    ctx.register_memtable("t_dist", schema(
        ("k", Int64), ("v", Int64)), {
        "k": rng.randint(0, 5, n).astype(np.int64),
        "v": rng.randint(0, 50, n).astype(np.int64),
    })
    df = ctx.sql("select k, count(distinct v) as dv from t_dist "
                 "where v > 4 group by k order by k")
    out = df.collect()
    phys = df._phys
    assert _count_type(phys, FusedDistinctCountExec) == 1, phys.pretty()
    # the whole double-agg tower AND the dedup partial are gone
    assert _count_type(phys, HashAggregateExec) == 0, phys.pretty()

    # oracle over the registered arrays
    import pandas as pd

    raw = ctx.sql("select k, v from t_dist").collect()
    k = np.asarray(raw["k"])
    v = np.asarray(raw["v"])
    exp = (pd.DataFrame({"k": k, "v": v}).query("v > 4")
           .groupby("k")["v"].nunique().reset_index()
           .rename(columns={"v": "dv"}).sort_values("k")
           .reset_index(drop=True))
    assert list(out["k"]) == list(exp["k"])
    assert list(out["dv"]) == list(exp["dv"])


# ---------------------------------------------------------------------------
# AOT export/load (BALLISTA_FUSION_AOT_DIR)
# ---------------------------------------------------------------------------


def test_aot_export_then_load(tpch_dir, tmp_path, monkeypatch):
    """First run exports fused-stage programs; after clearing the
    in-process governor (standing in for a fresh process) the next run
    LOADS them — no re-trace — and stays byte-identical."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.compile import compile_stats, governor
    from benchmarks.tpch.schema_def import register_tpch

    aot = str(tmp_path / "aot")
    monkeypatch.setenv("BALLISTA_FUSION_AOT_DIR", aot)
    monkeypatch.setenv("BALLISTA_FUSION", "on")
    sql = open(os.path.join(QDIR, "q1.sql")).read()
    ctx = BallistaContext.standalone()
    register_tpch(ctx, tpch_dir, "tbl")
    first = ctx.sql(sql).collect()
    deadline = time.time() + 30
    while time.time() < deadline:  # background export
        if os.path.isdir(aot) and os.listdir(aot):
            break
        time.sleep(0.2)
    assert os.path.isdir(aot) and os.listdir(aot), "no AOT artifact"
    governor().clear()  # fresh-process stand-in: all entries gone
    base_loads = int(compile_stats()["aot_loads"])
    ctx2 = BallistaContext.standalone()
    register_tpch(ctx2, tpch_dir, "tbl")
    second = ctx2.sql(sql).collect()
    assert int(compile_stats()["aot_loads"]) > base_loads, \
        "fused stage was re-traced instead of AOT-loaded"
    _assert_byte_identical(first, second, "q1[aot]")


def test_aot_off_by_default(monkeypatch):
    monkeypatch.delenv("BALLISTA_FUSION_AOT_DIR", raising=False)
    from ballista_tpu.compile.aot import aot_dir, make_entry

    assert aot_dir() is None
    assert make_entry(("agg.grouped", "x")) is None


# ---------------------------------------------------------------------------
# prewarm targets fused-stage signatures
# ---------------------------------------------------------------------------


def test_prewarm_targets_fused_stage(tpch_dir, monkeypatch):
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.compile.prewarm import collect_targets
    from ballista_tpu.execution import plan_logical
    from ballista_tpu.physical.fusion import maybe_fuse
    from ballista_tpu.physical.planner import PlannerOptions
    from benchmarks.tpch.schema_def import register_tpch

    monkeypatch.setenv("BALLISTA_FUSION", "on")
    ctx = BallistaContext.standalone()
    register_tpch(ctx, tpch_dir, "tbl")
    sql = open(os.path.join(QDIR, "q1.sql")).read()
    phys = maybe_fuse(plan_logical(
        ctx.sql(sql)._plan, PlannerOptions.from_settings(ctx.settings)))
    targets = collect_targets(phys)
    assert targets, "fused q1 stage must be a prewarm target"
    fn, batch = targets[0]
    assert fn.warm(batch) in (True, False)  # lowering must not raise


# ---------------------------------------------------------------------------
# program-count regression gate (dev/check_jit_sites.py --budget)
# ---------------------------------------------------------------------------


def test_program_budget_gate():
    """q1+q5 with fusion ON must mint no more governed entries than the
    pinned budget, and the fused operators must actually be in the
    plans — fails on silent de-fusion. Subprocess: the gate needs a
    clean process-wide governor."""
    proc = subprocess.run(
        [sys.executable, os.path.join(DEV, "check_jit_sites.py"),
         "--budget"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "BALLISTA_METRICS": "0"},
        cwd=os.path.join(os.path.dirname(__file__), ".."),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
