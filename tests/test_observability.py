"""Observability subsystem tests: operator metrics, EXPLAIN ANALYZE,
cluster telemetry round-trip, tracing, and the metrics-overhead gate.

The gate test (q1 SF0.01 overhead < 5%) is what keeps the "lock-cheap"
claim honest: metrics default ON, so a regression in the instrument
wrapper would silently tax every query.
"""

import glob
import json
import os
import time

import numpy as np
import pytest

from ballista_tpu.client import BallistaContext
from ballista_tpu.datatypes import Float64, Int64, Utf8, schema
from ballista_tpu.observability import metrics as obs_metrics
from ballista_tpu.observability import tracing as obs_tracing
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu import serde


@pytest.fixture
def ctx():
    c = BallistaContext.standalone()
    c.register_memtable(
        "t", schema(("k", Utf8), ("a", Int64), ("b", Float64)),
        {"k": ["x", "y", "x", "y", "z"] * 8,
         "a": list(range(40)),
         "b": [float(i) / 2 for i in range(40)]},
    )
    c.register_memtable(
        "u", schema(("k", Utf8), ("w", Int64)),
        {"k": ["x", "y", "z"], "w": [10, 20, 30]},
    )
    return c


@pytest.fixture
def metrics_env():
    """Restore metric enablement however a test mangles it."""
    saved = os.environ.get("BALLISTA_METRICS")
    yield
    if saved is None:
        os.environ.pop("BALLISTA_METRICS", None)
    else:
        os.environ["BALLISTA_METRICS"] = saved
    obs_metrics.reconfigure()


@pytest.fixture
def trace_env(tmp_path):
    """Enable tracing into a tmp file; restore + re-disable afterwards."""
    saved = {k: os.environ.get(k)
             for k in ("BALLISTA_TRACE", "BALLISTA_TRACE_FILE",
                       "BALLISTA_TRACE_DIR")}
    path = str(tmp_path / "trace.jsonl")
    os.environ["BALLISTA_TRACE"] = "1"
    os.environ["BALLISTA_TRACE_FILE"] = path
    obs_tracing.reconfigure()
    yield path
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    obs_tracing.reconfigure()


def _op_rows(qm, prefix):
    return [r for r in qm.operators() if r["operator"].startswith(prefix)]


# ---------------------------------------------------------------------------
# (a) operator metrics populate on a local query
# ---------------------------------------------------------------------------


def test_metrics_populate_scan_filter_agg_join(ctx):
    out = ctx.sql(
        "SELECT t.k, sum(t.a) AS s, sum(u.w) AS ws FROM t "
        "JOIN u ON t.k = u.k WHERE t.a > 0 GROUP BY t.k"
    ).collect()
    assert len(out) == 3
    qm = ctx.last_query_metrics()
    assert qm is not None and qm.stage_ids() == [0]
    for prefix in ("ScanExec", "FilterExec", "HashAggregateExec",
                   "JoinExec"):
        rows = _op_rows(qm, prefix)
        assert rows, f"no {prefix} row in {qm.pretty()}"
        live = [r for r in rows if "[fused]" not in r["operator"]]
        if live:
            m = live[0]["metrics"]
            assert m.get("output_rows", 0) > 0, (prefix, m)
            assert m.get("elapsed_compute", 0.0) > 0.0, (prefix, m)
        else:
            # whole-stage fusion absorbed the operator into a fused
            # program: it still gets a marked row, and its work is
            # attributed to the fused host operator's metrics
            assert all("[fused]" in r["operator"] for r in rows)
    # scans saw every row of their table
    scans = _op_rows(qm, "ScanExec")
    assert sorted(r["metrics"]["output_rows"] for r in scans) == [3, 40]
    # derived self-time never exceeds cumulative time
    for r in qm.operators():
        m = r["metrics"]
        if "elapsed_self" in m:
            assert m["elapsed_self"] <= m["elapsed_compute"] + 1e-9
    assert qm.total_output_rows() == 3


def test_repeat_collect_reports_single_run(ctx):
    # the plan cache reuses the physical plan across collects; metrics
    # must reset per run, not accumulate (and pending device row-count
    # scalars must drain rather than grow with every batch)
    sql = "SELECT k, sum(a) AS s FROM t GROUP BY k"
    for _ in range(3):
        ctx.sql(sql).collect()
        qm = ctx.last_query_metrics()
        scans = _op_rows(qm, "ScanExec")
        assert [r["metrics"]["output_rows"] for r in scans] == [40]


def test_metrics_disabled_yields_none(ctx, metrics_env):
    os.environ["BALLISTA_METRICS"] = "0"
    obs_metrics.reconfigure()
    ctx.sql("SELECT sum(a) AS s FROM t").collect()
    assert ctx.last_query_metrics() is None


# ---------------------------------------------------------------------------
# (b) EXPLAIN ANALYZE carries row counts and timings
# ---------------------------------------------------------------------------


def test_explain_analyze_rows_annotated(ctx):
    out = ctx.sql(
        "EXPLAIN ANALYZE SELECT k, sum(a) AS s FROM t GROUP BY k"
    ).collect()
    rows = dict(zip(out["plan_type"], out["plan"]))
    plan = rows["plan_with_metrics"]
    assert "output_rows=" in plan and "elapsed_compute=" in plan
    assert "HashAggregateExec" in plan and "ScanExec" in plan
    assert float(rows["total_elapsed"].rstrip("s")) > 0.0


def test_explain_analyze_repeat_does_not_accumulate(ctx):
    # the standalone plan cache reuses the physical plan; ANALYZE must
    # reset its MetricsSets or the second run reports doubled numbers
    sql = "EXPLAIN ANALYZE SELECT k, sum(a) AS s FROM t GROUP BY k"
    import re

    def scan_rows(plan):
        m = re.search(r"ScanExec.*?output_rows=(\d+)", plan)
        return int(m.group(1))

    for _ in range(2):
        out = ctx.sql(sql).collect()
        plan = dict(zip(out["plan_type"], out["plan"]))["plan_with_metrics"]
        assert scan_rows(plan) == 40


def test_explain_analyze_verbose_and_flag_order(ctx):
    for sql in ("EXPLAIN ANALYZE VERBOSE SELECT k FROM t",
                "EXPLAIN VERBOSE ANALYZE SELECT k FROM t"):
        out = ctx.sql(sql).collect()
        types = list(out["plan_type"])
        assert "logical_plan" in types and "plan_with_metrics" in types


def test_dataframe_explain_analyze_verb(ctx):
    from ballista_tpu import expr as ex

    df = (ctx.table("t").filter(ex.col("a") > ex.lit(2))
          .aggregate([ex.col("k")], [ex.sum_(ex.col("a")).alias("s")]))
    txt = df.explain_analyze()
    assert "output_rows=" in txt and "elapsed_compute=" in txt
    assert "FilterExec" in txt and "HashAggregateExec" in txt


def test_explain_analyze_measures_even_when_disabled(ctx, metrics_env):
    # force_metrics: ANALYZE must measure under BALLISTA_METRICS=0
    os.environ["BALLISTA_METRICS"] = "0"
    obs_metrics.reconfigure()
    out = ctx.sql("EXPLAIN ANALYZE SELECT sum(a) AS s FROM t").collect()
    plan = dict(zip(out["plan_type"], out["plan"]))["plan_with_metrics"]
    assert "output_rows=" in plan


# ---------------------------------------------------------------------------
# (c) cluster path round-trips TaskMetrics through the scheduler
# ---------------------------------------------------------------------------


def test_task_metrics_proto_roundtrip():
    tm = {
        "operators": [
            {"operator": "ScanExec: t", "depth": 1,
             "metrics": {"output_rows": 40, "elapsed_compute": 0.5,
                         "selectivity": 0.25}},
            {"operator": "ShuffleWrite", "depth": 0,
             "metrics": {"bytes_written": 1394}},
        ],
        "elapsed_total": 1.25,
    }
    msg = pb.TaskMetrics()
    serde.task_metrics_to_proto(tm, msg)
    back = serde.task_metrics_from_proto(msg)
    assert back == tm  # counters int, timers/gauges float — kinds survive


def test_total_output_rows_uses_final_stage():
    # multi-stage: earlier stages feed shuffles; only the last stage's
    # root is the query output
    qm = obs_metrics.QueryMetrics({
        1: {"num_tasks": 4, "elapsed_total": 0.4,
            "operators": [{"operator": "ShuffleWrite", "depth": 0,
                           "metrics": {"output_rows": 16}}]},
        2: {"num_tasks": 1, "elapsed_total": 0.1,
            "operators": [{"operator": "HashAggregateExec", "depth": 0,
                           "metrics": {"output_rows": 2}}]},
    })
    assert qm.total_output_rows() == 2


def test_integral_gauge_keeps_kind_and_maxes_on_merge():
    # set_gauge(x, 1.0) must stay a gauge through the wire and be
    # max-ed (not summed) when tasks of a stage merge
    ms = obs_metrics.MetricsSet()
    ms.set_gauge("selectivity", 1)  # int input coerced to float
    row = {"operator": "FilterExec", "depth": 0, "metrics": ms.values()}
    msg = pb.TaskMetrics()
    serde.task_metrics_to_proto({"operators": [row]}, msg)
    back = serde.task_metrics_from_proto(msg)
    v = back["operators"][0]["metrics"]["selectivity"]
    assert isinstance(v, float) and v == 1.0
    merged = obs_metrics.merge_operator_metrics(
        [back["operators"], back["operators"], back["operators"]])
    assert merged[0]["metrics"]["selectivity"] == 1.0  # max, not 3


def test_elapsed_self_sums_within_total(ctx):
    # fused pipeline intermediates record no own time; self-time
    # attribution must not double count their subtree (sum of
    # elapsed_self across the plan stays within the root's cumulative)
    ctx.sql("SELECT k, sum(a) AS s FROM t WHERE a > 1 GROUP BY k").collect()
    qm = ctx.last_query_metrics()
    ops = qm.operators()
    root_total = ops[0]["metrics"]["elapsed_compute"]
    self_sum = sum(r["metrics"].get("elapsed_self", 0.0) for r in ops)
    assert self_sum <= root_total * 1.001 + 1e-9, qm.pretty()


def test_stage_metrics_proto_roundtrip():
    stages = {
        1: {"num_tasks": 2, "elapsed_total": 0.75,
            "operators": [{"operator": "ScanExec", "depth": 0,
                           "metrics": {"output_rows": 80,
                                       "elapsed_compute": 0.25}}]},
    }
    job = pb.CompletedJob()
    serde.stage_metrics_to_proto(stages, job.stage_metrics)
    assert serde.stage_metrics_from_proto(job.stage_metrics) == stages


def test_cluster_metrics_and_trace(tmp_path, trace_env):
    from ballista_tpu.distributed.executor import LocalCluster

    csv = tmp_path / "t.csv"
    with open(csv, "w") as f:
        f.write("k,a\n")
        for i in range(40):
            f.write(f"{'xy'[i % 2]},{i}\n")

    cluster = LocalCluster(num_executors=2)
    try:
        ctx = BallistaContext.remote("localhost", cluster.port)
        ctx.register_csv("t", str(csv), schema(("k", Utf8), ("a", Int64)))
        out = ctx.sql(
            "SELECT k, sum(a) AS s FROM t GROUP BY k ORDER BY k"
        ).collect()
        assert list(out["s"]) == [380, 400]

        qm = ctx.last_query_metrics()
        assert qm is not None and len(qm.stage_ids()) >= 2, repr(qm)
        # per-stage aggregation reached the client: operator rows carry
        # rows + timings, the shuffle reader read bytes, writers wrote
        assert _op_rows(qm, "ScanExec")[0]["metrics"]["output_rows"] == 40
        reader = _op_rows(qm, "ShuffleReaderExec")
        assert reader and reader[0]["metrics"].get("bytes_read", 0) > 0
        writes = [r for r in qm.operators()
                  if r["operator"] in ("PartitionWrite", "ShuffleWrite")]
        assert writes and all(
            r["metrics"].get("bytes_written", 0) > 0 for r in writes)
        for st in qm.stages.values():
            assert st["num_tasks"] >= 1 and st["elapsed_total"] > 0.0

        # EXPLAIN ANALYZE rides the cluster result channel annotated
        out = ctx.sql(
            "EXPLAIN ANALYZE SELECT k, sum(a) AS s FROM t GROUP BY k"
        ).collect()
        plan = dict(zip(out["plan_type"], out["plan"]))["plan_with_metrics"]
        assert "output_rows=" in plan and "elapsed_compute=" in plan
    finally:
        cluster.shutdown()

    # (d, cluster half) the run above emitted spans for every subsystem
    spans = [json.loads(line) for line in open(trace_env)]
    names = {s["name"] for s in spans}
    assert {"scheduler.plan_job", "scheduler.task_dispatch",
            "executor.task", "dataplane.write"} <= names, names


# ---------------------------------------------------------------------------
# (d) BALLISTA_TRACE=1 emits parseable span JSON
# ---------------------------------------------------------------------------


def test_trace_span_and_event_schema(trace_env):
    from ballista_tpu.observability import trace_event, trace_span

    assert obs_tracing.trace_enabled()
    trace_event("test.instant", detail="x")
    with trace_span("test.span", task="t1"):
        time.sleep(0.01)
    with pytest.raises(ValueError):
        with trace_span("test.error"):
            raise ValueError("boom")

    recs = [json.loads(line) for line in open(trace_env)]
    by_name = {r["name"]: r for r in recs}
    inst = by_name["test.instant"]
    assert inst["detail"] == "x" and "dur" not in inst
    span = by_name["test.span"]
    assert span["dur"] >= 0.01 and span["task"] == "t1"
    assert by_name["test.error"]["error"] == "ValueError"
    for r in recs:  # common schema
        assert isinstance(r["ts"], float)
        assert isinstance(r["pid"], int) and isinstance(r["tid"], int)


def test_trace_disabled_by_default(tmp_path):
    saved = os.environ.pop("BALLISTA_TRACE", None)
    obs_tracing.reconfigure()
    try:
        assert not obs_tracing.trace_enabled()
        from ballista_tpu.observability import trace_event

        trace_event("test.noop")  # must not raise, must not write
        assert not glob.glob(str(tmp_path / "*.jsonl"))
    finally:
        if saved is not None:
            os.environ["BALLISTA_TRACE"] = saved
        obs_tracing.reconfigure()


# ---------------------------------------------------------------------------
# (e) metrics overhead gate: q1 @ SF0.01 < 5%
# ---------------------------------------------------------------------------


def test_metrics_overhead_q1_under_5pct(tmp_path_factory, metrics_env):
    from benchmarks.tpch import datagen
    from benchmarks.tpch.schema_def import register_tpch

    data_dir = str(tmp_path_factory.mktemp("tpch_obs"))
    datagen.generate(data_dir, scale=0.01, num_parts=1)
    ctx = BallistaContext.standalone()
    register_tpch(ctx, data_dir, "tbl")
    qdir = os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                        "tpch", "queries")
    df = ctx.sql(open(os.path.join(qdir, "q1.sql")).read())
    df.collect()  # warm: jit compile + table caches

    def sample(flag):
        os.environ["BALLISTA_METRICS"] = flag
        obs_metrics.reconfigure()
        t0 = time.perf_counter()
        for _ in range(3):  # longer samples so jitter shrinks vs budget
            df.collect()
        return time.perf_counter() - t0

    # settle adaptive/jit state on both paths before measuring
    sample("1")
    sample("0")

    def measure():
        # interleaved pairs with ALTERNATING order (off/on, on/off, ...)
        # so both a load spike and a monotonic load ramp hit the two
        # sides equally; medians absorb what alternation doesn't cancel
        # (profiling puts the true wrapper cost at ~0.1ms/collect —
        # everything else here is machine noise)
        offs, ons = [], []
        for i in range(9):
            if i % 2 == 0:
                offs.append(sample("0"))
                ons.append(sample("1"))
            else:
                ons.append(sample("1"))
                offs.append(sample("0"))
        return sorted(offs)[4], sorted(ons)[4]

    # up to 3 attempts: a co-tenant CPU burst can still push one
    # measurement over the line, but a REAL >5% regression fails all
    # three; the 2ms absolute floor covers runs whose whole 5% budget
    # is itself only a few milliseconds
    for attempt in range(3):
        t_off, t_on = measure()
        if t_on <= t_off * 1.05 + 2e-3:
            return
    overhead = (t_on - t_off) / t_off
    raise AssertionError(
        f"metrics overhead {overhead:.1%} (on={t_on:.4f}s off={t_off:.4f}s)"
    )
