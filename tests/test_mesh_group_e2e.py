"""Mesh-group end to end: a fused SQL aggregation spanning TWO executor
PROCESSES that share one 8-device mesh (4 virtual devices each).

This is the multi-host scale-out shape (SURVEY §5.8): the scheduler
sees the group as one executor reporting 8 devices, fuses the shuffle
stage pair into a MeshAggExec, the leader broadcasts the task over the
group channel, and the `lax.all_to_all` row exchange crosses the
process boundary inside the jax.distributed runtime — no shuffle files
anywhere, results verified against pandas.
"""

import os
import re
import signal
import socket
import subprocess

import numpy as np
import pandas as pd
import pytest

from ballista_tpu import Int64, Utf8, schema
from tests.procutil import spawn_module as _spawn


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.mark.sf02  # heavyweight: spawns a 3-process cluster
def test_fused_aggregation_across_process_mesh(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    repo = os.path.join(os.path.dirname(__file__), "..")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    # multi-file table -> table-wide dictionaries (content-identical
    # across the group's processes, required for fused utf8 keys)
    d = tmp_path / "t"
    d.mkdir()
    rng = np.random.default_rng(23)
    keys = [f"g{k}" for k in rng.integers(0, 29, 900)]
    vals = rng.integers(0, 500, 900)
    for part in range(3):
        rows = [f"{keys[i]}|{vals[i]}|" for i in range(900)
                if i % 3 == part]
        (d / f"p{part}.tbl").write_text("\n".join(rows) + "\n")

    coord = _free_port()
    chan = _free_port()
    procs = []
    try:
        sched = _spawn(["ballista_tpu.distributed.scheduler_main",
                        "--bind-host", "localhost", "--port", "0"], env)
        procs.append(sched)
        line = sched.wait_for(lambda ln: "listening on" in ln)
        m = re.search(r"listening on [^:]+:(\d+)", line)
        assert m, f"no port in scheduler output: {line!r}"
        sport = m.group(1)

        common = ["--scheduler-host", "localhost",
                  "--scheduler-port", sport,
                  "--mesh-group-size", "2",
                  "--mesh-group-coordinator", f"localhost:{coord}",
                  "--mesh-group-channel", f"localhost:{chan}",
                  "--mesh-local-devices", "4"]
        leader = _spawn(["ballista_tpu.distributed.executor_main",
                         *common, "--mesh-group-rank", "0",
                         "--work-dir", str(tmp_path / "w0")], env)
        procs.append(leader)
        follower = _spawn(["ballista_tpu.distributed.executor_main",
                           *common, "--mesh-group-rank", "1",
                           "--work-dir", str(tmp_path / "w1")], env)
        procs.append(follower)

        # leader prints its polling line only after the follower joined
        polling = leader.wait_for(lambda ln: "polling" in ln, timeout=90)
        assert "mesh group of 2 x 4 devices" in polling, leader.text

        from ballista_tpu.client import BallistaContext
        from ballista_tpu.io import TblSource

        # claim the mesh width so planning waits for the leader's first
        # poll instead of racing it (unclaimed + unregistered -> unfused)
        ctx = BallistaContext.remote("localhost", int(sport),
                                     **{"agg.partitions": "8",
                                        "mesh.devices": "8"})
        ctx.register_source(
            "t", TblSource(str(d), schema(("k", Utf8), ("v", Int64))))
        got = ctx.sql(
            "select k, sum(v) as sv, count(*) as n from t "
            "group by k order by k"
        ).collect()

        exp = pd.DataFrame({"k": keys, "v": vals}).groupby("k").agg(
            sv=("v", "sum"), n=("v", "size")).reset_index().sort_values("k")
        np.testing.assert_array_equal(got["k"], exp["k"])
        np.testing.assert_array_equal(got["sv"].astype(np.int64),
                                      exp["sv"].astype(np.int64))
        np.testing.assert_array_equal(got["n"].astype(np.int64),
                                      exp["n"].astype(np.int64))

        # fused across the group: zero shuffle files in EITHER work dir
        files = []
        for w in ("w0", "w1"):
            for root, _, fs in os.walk(tmp_path / w):
                files += [f for f in fs if f.startswith("shuffle-")]
        assert files == [], f"host shuffle files written: {files}"

        # same cluster, q5 shape: a partitioned JOIN fused across the
        # process mesh (MeshJoinExec collectives cross the boundary too)
        dim = tmp_path / "dim"
        dim.mkdir()
        (dim / "p0.tbl").write_text(
            "".join(f"{i}|cat{i % 4}|\n" for i in range(13)))
        fact = tmp_path / "fact"
        fact.mkdir()
        fk = rng.integers(0, 13, 400)
        fv = rng.integers(0, 100, 400)
        for part in range(2):
            rows = [f"{i}|{fk[i]}|{fv[i]}|\n" for i in range(400)
                    if i % 2 == part]
            (fact / f"p{part}.tbl").write_text("".join(rows))
        from ballista_tpu import Decimal

        ctx2 = BallistaContext.remote(
            "localhost", int(sport),
            **{"join.partitioned.threshold": "1", "join.partitions": "8",
               "agg.partitions": "8", "mesh.devices": "8"},
        )
        ctx2.register_source(
            "dim", TblSource(str(dim), schema(("dkey", Int64),
                                              ("cat", Utf8))),
            primary_key="dkey")
        ctx2.register_source(
            "fact", TblSource(str(fact), schema(("fid", Int64),
                                                ("fkey", Int64),
                                                ("v", Int64))))
        got2 = ctx2.sql(
            "select cat, sum(v) as sv, count(*) as n from fact, dim "
            "where fkey = dkey group by cat order by cat"
        ).collect()
        fd = pd.DataFrame({"fkey": fk, "v": fv})
        fd["cat"] = fd.fkey.map(lambda k: f"cat{k % 4}")
        exp2 = fd.groupby("cat").agg(sv=("v", "sum"), n=("v", "size")) \
            .reset_index().sort_values("cat")
        np.testing.assert_array_equal(got2["cat"], exp2["cat"])
        np.testing.assert_array_equal(got2["sv"].astype(np.int64),
                                      exp2["sv"].astype(np.int64))
        np.testing.assert_array_equal(got2["n"].astype(np.int64),
                                      exp2["n"].astype(np.int64))
        files = []
        for w in ("w0", "w1"):
            for root, _, fs in os.walk(tmp_path / w):
                files += [f for f in fs if f.startswith("shuffle-")]
        assert files == [], f"join wrote host shuffle files: {files}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
