"""EtcdBackend against a REAL etcd server (protocol-skew guard).

The etcd v3 wire implementation is normally exercised only against the
in-repo FakeEtcdServer; skew between that fake and a real server is a
classic failure mode (the reference's compose gate runs actual etcd,
reference: rust/benchmarks/tpch/docker-compose.yaml:1-46). These tests
run whenever a real endpoint is available:

- ``BALLISTA_ETCD_URL=host:port`` points at a running etcd, or
- an ``etcd`` binary on PATH is started on ephemeral ports.

Otherwise they skip (no etcd binary ships in the dev image; the compose
overlay ``deploy/docker-compose.etcd.yaml`` is the environment that
provides one — run this file inside it for the full gate).
"""

import os
import shutil
import socket
import subprocess
import tempfile
import time

import pytest

from ballista_tpu.distributed.etcd import EtcdBackend


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.fixture(scope="module")
def real_etcd_url():
    url = os.environ.get("BALLISTA_ETCD_URL")
    if url:
        yield url
        return
    binary = shutil.which("etcd")
    if binary is None:
        pytest.skip("no real etcd available (set BALLISTA_ETCD_URL or "
                    "install etcd; see deploy/docker-compose.etcd.yaml)")
    client_port, peer_port = _free_port(), _free_port()
    data_dir = tempfile.mkdtemp(prefix="etcd-test-")
    proc = subprocess.Popen(
        [binary,
         "--data-dir", data_dir,
         "--listen-client-urls", f"http://localhost:{client_port}",
         "--advertise-client-urls", f"http://localhost:{client_port}",
         "--listen-peer-urls", f"http://localhost:{peer_port}",
         "--initial-advertise-peer-urls", f"http://localhost:{peer_port}",
         "--initial-cluster", f"default=http://localhost:{peer_port}"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    url = f"localhost:{client_port}"
    # wait for readiness
    deadline = time.time() + 15
    last = None
    while time.time() < deadline:
        try:
            b = EtcdBackend(url)
            b.put("/ready", b"1")
            assert b.get("/ready") == b"1"
            b.close()
            break
        except Exception as e:  # noqa: BLE001 - still booting
            last = e
            time.sleep(0.3)
    else:
        proc.terminate()
        pytest.skip(f"etcd never became ready: {last}")
    yield url
    proc.terminate()
    proc.wait(timeout=10)
    shutil.rmtree(data_dir, ignore_errors=True)


@pytest.fixture()
def backend(real_etcd_url):
    b = EtcdBackend(real_etcd_url)
    yield b
    # namespace hygiene between tests
    for k, _ in b.get_from_prefix("/"):
        b.delete(k)
    b.close()


def test_real_etcd_kv_roundtrip(backend):
    backend.put("/ballista/ns/a", b"1")
    backend.put("/ballista/ns/b", b"2")
    assert backend.get("/ballista/ns/a") == b"1"
    assert backend.get("/missing") is None
    got = backend.get_from_prefix("/ballista/ns/")
    assert got == [("/ballista/ns/a", b"1"), ("/ballista/ns/b", b"2")]
    backend.delete("/ballista/ns/a")
    assert backend.get("/ballista/ns/a") is None


def test_real_etcd_lease_expiry(backend):
    backend.put("/lease/k", b"v", lease_secs=1)
    assert backend.get("/lease/k") == b"v"
    time.sleep(2.5)  # real etcd lease granularity is 1s + election slack
    assert backend.get("/lease/k") is None


def test_real_etcd_lock_mutual_exclusion(real_etcd_url):
    import threading

    b1 = EtcdBackend(real_etcd_url, lock_ttl_secs=5)
    b2 = EtcdBackend(real_etcd_url, lock_ttl_secs=5)
    order = []
    try:
        def worker(b, tag):
            with b.lock():
                order.append((tag, "in"))
                time.sleep(0.1)
                order.append((tag, "out"))

        ts = [threading.Thread(target=worker, args=(b, i))
              for i, b in enumerate((b1, b2))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for i in range(0, len(order), 2):
            assert order[i][0] == order[i + 1][0]
    finally:
        b1.close()
        b2.close()


def test_real_etcd_scheduler_state(backend):
    """The scheduler state machine over a real etcd: save/rehydrate."""
    from ballista_tpu.distributed.state import SchedulerState
    from ballista_tpu.distributed.types import JobStatus

    st = SchedulerState(backend, namespace="realetcd")
    st.save_job_status("jr1", JobStatus("queued"))
    st.save_stage_plan("jr1", 1, b"planbytes", 2, [])
    # a second state instance (fresh scheduler process) sees the same world
    st2 = SchedulerState(backend, namespace="realetcd")
    assert st2.get_job_status("jr1").state == "queued"
    assert st2.stage_ids("jr1") == [1]
