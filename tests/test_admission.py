"""Overload-safe multi-tenant admission plane: quotas, priorities, load
shedding (docs/robustness.md "Admission & overload").

The scheduler accepted every ExecuteQuery unconditionally before this
plane: a burst of concurrent sessions could queue unbounded work,
starve each other, and blow past the budgets the metering plane
accounts per session. These tests pin the degradation ladder
(admit -> queue -> shed), the structured retryable shed contract, the
bounds on every waiting job (queue timeout, deadline, CancelJob), the
client's retry-after handling, and the overload chaos sweep: K sessions
submitting 3x cluster capacity with injected admission faults, every
admitted query byte-identical to an unloaded run, zero hangs.

Also pins the riding satellites: rate-based speculation off the live
progress samples (ROADMAP 5a), the scheduler-state leak purge at
terminal transitions, and the BALLISTA_MAX_TASK_RECOVERIES knob.

Style: service-level tests use direct calls like test_lifecycle.py;
e2e gates run a real LocalCluster.
"""

import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from ballista_tpu import Int64, Utf8, col, schema, serde, sum_
from ballista_tpu.client import BallistaContext
from ballista_tpu.distributed.admission import (
    AdmissionConfig,
    AdmissionController,
    Decision,
)
from ballista_tpu.distributed.executor import LocalCluster
from ballista_tpu.distributed.scheduler import SchedulerService
from ballista_tpu.distributed.state import MemoryBackend, SchedulerState
from ballista_tpu.distributed.types import (
    JobStatus,
    PartitionId,
    TaskStatus,
)
from ballista_tpu.errors import AdmissionRejected, QueryCancelled
from ballista_tpu.io import TblSource
from ballista_tpu.logical import LogicalPlanBuilder
from ballista_tpu.observability.progress import JobProgressTracker
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.testing import faults as faults_mod
from ballista_tpu.testing.faults import reload_faults

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

TSCHEMA = schema(("a", Int64), ("c", Utf8))
GROUPBY_SQL = "select c, sum(a) as s from t group by c order by c"
N_ROWS = 120


@pytest.fixture
def faults_env():
    saved = os.environ.get("BALLISTA_FAULTS")

    def arm(spec: str):
        if spec:
            os.environ["BALLISTA_FAULTS"] = spec
        else:
            os.environ.pop("BALLISTA_FAULTS", None)
        reload_faults()

    yield arm
    if saved is None:
        os.environ.pop("BALLISTA_FAULTS", None)
    else:
        os.environ["BALLISTA_FAULTS"] = saved
    reload_faults()


def _wait_until(cond, timeout: float, msg: str):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(msg)


def _write_tbl(tmp_path, rows: int = N_ROWS, parts: int = 2) -> str:
    d = tmp_path / "t"
    d.mkdir(exist_ok=True)
    for part in range(parts):
        lines = [f"{i}|k{i % 7}|" for i in range(rows) if i % parts == part]
        (d / f"part{part}.tbl").write_text("\n".join(lines) + "\n")
    return str(d)


def _expected(rows: int = N_ROWS) -> pd.DataFrame:
    df = pd.DataFrame({"a": range(rows),
                       "c": [f"k{i % 7}" for i in range(rows)]})
    return (df.groupby("c", as_index=False)["a"].sum()
            .rename(columns={"a": "s"})
            .sort_values("c").reset_index(drop=True))


def _assert_identical(got: pd.DataFrame, exp: pd.DataFrame):
    assert list(got.columns) == list(exp.columns)
    assert len(got) == len(exp)
    for name in exp.columns:
        g, e = got[name].to_numpy(), exp[name].to_numpy()
        assert np.array_equal(g, e), f"column {name}: {g} != {e}"


def _service() -> SchedulerService:
    return SchedulerService(SchedulerState(MemoryBackend()))


def _submit(svc, src, settings=None, deadline_secs: float = 0.0):
    plan = (LogicalPlanBuilder.scan("t", src)
            .aggregate([col("c")], [sum_(col("a")).alias("s")])
            .build())
    params = pb.ExecuteQueryParams()
    params.logical_plan.CopyFrom(serde.plan_to_proto(plan))
    for k, v in (settings or {}).items():
        params.settings[k] = v
    if deadline_secs:
        params.deadline_secs = deadline_secs
    return svc.ExecuteQuery(params)


# ---------------------------------------------------------------------------
# (a) configuration: admission.* > BALLISTA_ADMISSION_* > defaults
# ---------------------------------------------------------------------------


def test_admission_config_precedence(monkeypatch):
    # defaults: everything unlimited, enabled, bounded queue
    cfg = AdmissionConfig.from_settings({})
    assert cfg.enabled and cfg.max_session_jobs == 0
    assert cfg.max_queue_depth == 64 and cfg.queue_timeout_secs == 30.0

    # env fallback
    monkeypatch.setenv("BALLISTA_ADMISSION_MAX_SESSION_JOBS", "4")
    monkeypatch.setenv("BALLISTA_ADMISSION_QUEUE_TIMEOUT_SECS", "7.5")
    cfg = AdmissionConfig.from_settings({})
    assert cfg.max_session_jobs == 4 and cfg.queue_timeout_secs == 7.5

    # settings win over env
    cfg = AdmissionConfig.from_settings(
        {"admission.max_session_jobs": "2", "admission.enabled": "off",
         "admission.priority": "-3"})
    assert cfg.max_session_jobs == 2 and not cfg.enabled
    assert cfg.priority == -3.0

    with pytest.raises(ValueError):
        AdmissionConfig.from_settings(
            {"admission.max_running_jobs": "banana"})
    with pytest.raises(ValueError):
        AdmissionConfig.from_settings(
            {"admission.queue_timeout_secs": "-1"})


def test_gate_ladder_units():
    """The decision ladder on a bare controller: unlimited admits,
    concurrency queues, budgets/queue-full/drain shed."""
    ctl = AdmissionController(state=None)
    d = ctl.gate("j1", {"session.id": "s1"})
    assert d.action == "admit"

    # session concurrency -> queue (transient: clears when a job ends)
    s = {"session.id": "s1", "admission.max_session_jobs": "1"}
    d = ctl.gate("j2", s)
    assert d.action == "queue" and d.reason == "session-concurrency"

    # global concurrency -> queue
    d = ctl.gate("j3", {"session.id": "s2",
                        "admission.max_running_jobs": "1"})
    assert d.action == "queue" and d.reason == "cluster-concurrency"

    # releasing the active job frees the session slot
    ctl.on_terminal("j1")
    d = ctl.gate("j4", s)
    assert d.action == "admit"

    # queue full -> shed (non-transient: bounded waiting is the point)
    ctl.enqueue(ctl.gate("j5", s), args=("j5",))
    assert ctl.gate("j6", {**s, "admission.max_queue_depth": "1"}
                    ).action == "shed"

    # ... but an ADMISSIBLE submission never pays for other tenants'
    # backlog: the queue-full backstop only applies to work that would
    # actually queue
    assert ctl.gate("j6b", {"session.id": "s-free",
                            "admission.max_queue_depth": "1"}
                    ).action == "admit"
    ctl.on_terminal("j6b")

    # disabled -> everything admits
    assert ctl.gate("j7", {**s, "admission.enabled": "off"}
                    ).action == "admit"

    # draining -> shed with the structured reason
    ctl.begin_drain()
    d = ctl.gate("j8", {"session.id": "s3"})
    assert d.action == "shed" and d.reason == "draining"
    err = d.error()
    assert isinstance(err, AdmissionRejected)
    assert AdmissionRejected.parse(str(err)) == ("draining",
                                                 err.retry_after_secs)


def test_gate_bad_config_is_loud():
    """A configured-but-broken quota must fail the submission loudly,
    not silently stop being enforced (the fail-open guard is for
    INTERNAL bugs, not user config errors)."""
    ctl = AdmissionController(state=None)
    with pytest.raises(ValueError, match="admission.max_session_jobs"):
        ctl.gate("j1", {"session.id": "s",
                        "admission.max_session_jobs": "banana"})
    # nothing was reserved or recorded for the failed submission
    assert not ctl._active_session and ctl.queue_depth() == 0


def test_queue_slot_reserved_atomically_with_decision():
    """The depth check and the queue reservation are ONE critical
    section: a queue decision occupies its slot immediately (args
    pending), so racing gates cannot grow the queue past the bound."""
    ctl = AdmissionController(state=None)
    s = {"session.id": "s1", "admission.max_session_jobs": "1",
         "admission.max_queue_depth": "2"}
    ctl.gate("j1", s)  # admit
    d2 = ctl.gate("j2", s)
    assert d2.action == "queue" and ctl.queue_depth() == 1
    d3 = ctl.gate("j3", s)  # second queue BEFORE enqueue() of d2
    assert d3.action == "queue" and ctl.queue_depth() == 2
    assert ctl.gate("j4", s).action == "shed"  # bound enforced
    # args-less entries are not launchable: the pump leaves them
    ctl.on_terminal("j1")
    ctl.pump(force=True)
    assert ctl.queue_depth() == 2
    # enqueue() attaches args without duplicating the entry
    ctl.enqueue(d2, args=("j2",))
    assert ctl.queue_depth() == 2
    launched = []
    ctl.launch_fn = launched.append
    ctl.pump(force=True)
    assert launched == [("j2",)] and ctl.queue_depth() == 1


def test_launch_failure_releases_slot_and_sheds():
    """A queued job whose planning launch raises must not sit
    status=queued forever holding its slot: the slot is released and
    the job is shed as a retryable failure."""
    boom = RuntimeError("can't start new thread")

    def bad_launch(args):
        raise boom

    sheds = []
    ctl = AdmissionController(state=None, launch_fn=bad_launch,
                              shed_fn=sheds.append)
    s = {"session.id": "s1", "admission.max_session_jobs": "1"}
    ctl.gate("j1", s)
    d2 = ctl.gate("j2", s)
    ctl.enqueue(d2, args=("j2",))
    ctl.on_terminal("j1")
    ctl.pump(force=True)
    assert sheds and sheds[0].job_id == "j2"
    assert sheds[0].reason == "launch-error"
    assert not ctl._active_session, "leaked concurrency slot"


def test_terminal_race_before_admission_drops_entry():
    """A queued job cancelled before the pump admits it (the terminal
    hook ran before the entry carried args) is dropped at launch time
    and its just-reserved slot is released."""
    class FakeState:
        def __init__(self):
            self.terminal = set()

        def get_job_status(self, job_id):
            class _S:
                state = "cancelled"
            return _S() if job_id in self.terminal else None

        def ready_queue_depth(self):
            return 0

        def get_executors_metadata(self):
            return []

    st = FakeState()
    launched = []
    ctl = AdmissionController(state=st, launch_fn=launched.append)
    s = {"session.id": "s1", "admission.max_session_jobs": "1"}
    ctl.gate("j1", s)
    d2 = ctl.gate("j2", s)
    ctl.enqueue(d2, args=("j2",))
    st.terminal.add("j2")  # cancel raced: job terminal while queued
    ctl.on_terminal("j1")
    ctl.pump(force=True)
    assert launched == []
    assert not ctl._active_session, "leaked slot for terminal job"
    assert ctl.queue_depth() == 0


def test_cancel_between_retry_attempts_stops_resubmission():
    """A ctx.cancel() landing while the client sleeps between
    admission-retry attempts must stop the loop — resubmitting a query
    the user cancelled breaks the cancel contract."""
    from ballista_tpu.distributed.client import (
        CancelRequested,
        _collect_with_admission_retry,
    )

    sink: list = []
    calls = []

    def submit():
        calls.append(1)
        # simulate: submission shed, and the user cancels during the
        # retry window (ctx.cancel drops the sentinel into the sink)
        sink.append(CancelRequested("client"))
        raise AdmissionRejected("saturated", 0.05)

    with pytest.raises(QueryCancelled) as ei:
        _collect_with_admission_retry(30.0, submit,
                                      lambda jid, left: None,
                                      job_id_out=sink)
    assert ei.value.reason == "client"
    assert len(calls) == 1, "resubmitted a cancelled query"


def test_gate_session_budget_sheds(monkeypatch):
    """Cumulative session budgets read the PR 10 metering table
    (system.sessions): an exhausted budget SHEDS (queueing would never
    clear it)."""
    from ballista_tpu.observability.progress import process_session_meter

    sid = f"budget-sess-{os.getpid()}-{time.time_ns()}"
    process_session_meter().record(sid, wall_seconds=1.0,
                                   task_seconds=5.0,
                                   bytes_shuffled=1 << 20)
    ctl = AdmissionController(state=None)
    base = {"session.id": sid}
    # over the task-seconds budget
    d = ctl.gate("j1", {**base, "admission.session_task_seconds": "4"})
    assert d.action == "shed" and d.reason == "session-task-seconds"
    # over the shuffle-bytes budget
    d = ctl.gate("j2", {**base, "admission.session_shuffle_bytes":
                        str(1 << 10)})
    assert d.action == "shed" and d.reason == "session-shuffle-bytes"
    # under budget admits
    d = ctl.gate("j3", {**base, "admission.session_task_seconds": "99"})
    assert d.action == "admit"
    # another session is unaffected
    d = ctl.gate("j4", {"session.id": sid + "-other",
                        "admission.session_task_seconds": "4"})
    assert d.action == "admit"


def test_queue_ordering_priority_then_deadline():
    """Pop order: priority (higher first), then server-side deadline
    (sooner first), then arrival."""
    ctl = AdmissionController(state=None)
    now = time.time()

    def entry(job, prio=0.0, deadline=None, t=0.0):
        d = Decision("queue", job, "s",
                     config=AdmissionConfig(priority=prio),
                     deadline_ts=deadline, enqueued_at=now + t)
        ctl.enqueue(d, args=(job,))

    entry("late", t=0.2)
    entry("urgent", prio=5.0, t=0.3)
    entry("deadline-soon", deadline=now + 1.0, t=0.4)
    entry("deadline-later", deadline=now + 60.0, t=0.1)
    order = [ctl.queue_info(j)["queue_position"]
             for j in ("urgent", "deadline-soon", "deadline-later",
                       "late")]
    assert order == [1, 2, 3, 4], order


# ---------------------------------------------------------------------------
# (b) service level: queue visibility, timeout shed, cancel/deadline bounds
# ---------------------------------------------------------------------------


def test_quota_queues_with_visible_position_then_admits(tmp_path):
    svc = _service()
    src = TblSource(_write_tbl(tmp_path), TSCHEMA)
    s = {"session.id": "sess-q", "admission.max_session_jobs": "1"}
    r1 = _submit(svc, src, s)
    r2 = _submit(svc, src, s)
    assert not r1.error and not r2.error
    _wait_until(lambda: svc.state.stage_ids(r1.job_id), 10,
                "first job never planned")
    # second job is admission-queued: GetJobStatus speaks queued with
    # position/reason, /debug/jobs and system.queries agree
    gs = svc.GetJobStatus(pb.GetJobStatusParams(job_id=r2.job_id))
    assert gs.status.WhichOneof("status") == "queued"
    assert gs.status.queued.queue_position == 1
    assert gs.status.queued.reason == "session-concurrency"
    assert svc.state.stage_ids(r2.job_id) == []  # planning deferred
    jobs = {j["job_id"]: j for j in svc._debug_jobs(None)}
    assert jobs[r2.job_id]["status"] == "queued"
    assert jobs[r2.job_id]["queue_position"] == 1
    rows = {r["job_id"]: r
            for r in svc.systables.table_rows("system.queries")}
    assert rows[r2.job_id]["status"] == "queued"
    assert rows[r2.job_id]["queue_position"] == 1

    # finishing (here: cancelling) the first job frees the slot; the
    # pump launches the queued job's planning
    svc.CancelJob(pb.CancelJobParams(job_id=r1.job_id, reason="client"))
    _wait_until(lambda: svc.admission.queue_depth() == 0
                and svc.state.stage_ids(r2.job_id), 10,
                "queued job never admitted after slot freed")
    svc.CancelJob(pb.CancelJobParams(job_id=r2.job_id, reason="client"))
    svc.close_health()


def test_queue_timeout_sheds_with_structured_retryable_error(tmp_path):
    svc = _service()
    src = TblSource(_write_tbl(tmp_path), TSCHEMA)
    s = {"session.id": "sess-t", "admission.max_session_jobs": "1",
         "admission.queue_timeout_secs": "0.2",
         "admission.retry_after_secs": "2.5"}
    r1 = _submit(svc, src, s)
    r2 = _submit(svc, src, s)
    time.sleep(0.3)
    svc.admission.pump(force=True)
    gs = svc.GetJobStatus(pb.GetJobStatusParams(job_id=r2.job_id))
    assert gs.status.WhichOneof("status") == "failed"
    assert gs.status.failed.retry_after_secs == pytest.approx(2.5)
    parsed = AdmissionRejected.parse(gs.status.failed.error)
    assert parsed == ("queue-timeout", 2.5)
    # the shed observed its queue wait in the histogram
    from ballista_tpu.observability.registry import histogram_snapshot

    fam = histogram_snapshot().get(
        "ballista_admission_queue_wait_seconds", [])
    assert any(dict(labels).get("outcome") == "shed"
               for labels, *_ in fam)
    svc.CancelJob(pb.CancelJobParams(job_id=r1.job_id))
    svc.close_health()


def test_cancel_and_deadline_bound_queued_jobs(tmp_path):
    """A waiting submission is never unbounded: CancelJob removes it
    from the admission queue, and its server-side deadline holds while
    queued (the reap pass cancels it, which drops the queue entry)."""
    svc = _service()
    src = TblSource(_write_tbl(tmp_path), TSCHEMA)
    s = {"session.id": "sess-c", "admission.max_session_jobs": "1"}
    r1 = _submit(svc, src, s)
    r2 = _submit(svc, src, s)
    assert svc.admission.queue_depth() == 1
    # CancelJob on the QUEUED job: terminal cancelled, queue emptied
    res = svc.CancelJob(pb.CancelJobParams(job_id=r2.job_id,
                                           reason="client"))
    assert res.cancelled
    assert svc.admission.queue_depth() == 0
    assert svc.state.get_job_status(r2.job_id).state == "cancelled"

    # deadline on a queued job: reaped on time
    r3 = _submit(svc, src, s, deadline_secs=0.1)
    assert svc.admission.queue_depth() == 1
    time.sleep(0.15)
    svc.state.reap_expired_jobs(min_interval_secs=0.0)
    st = svc.state.get_job_status(r3.job_id)
    assert st.state == "cancelled" and st.cancel_reason == "deadline"
    assert svc.admission.queue_depth() == 0
    svc.CancelJob(pb.CancelJobParams(job_id=r1.job_id))
    svc.close_health()


def test_admission_metrics_and_trace_events(tmp_path):
    svc = _service()
    src = TblSource(_write_tbl(tmp_path), TSCHEMA)
    s = {"session.id": "sess-m", "admission.max_session_jobs": "1",
         "admission.max_queue_depth": "1"}
    _submit(svc, src, s)
    _submit(svc, src, s)  # queued
    shed = _submit(svc, src, s)  # shed: queue full
    assert shed.error
    samples = {name: v for name, labels, v in svc._metric_samples()}
    assert samples["ballista_admission_queue_depth"] == 1
    assert samples["ballista_admission_admitted_total"] == 1
    assert samples["ballista_admission_queued_total"] == 1
    assert samples["ballista_admission_sheds_total"] == 1
    # decisions landed in system.admission with the gate's reasons
    rows = svc.systables.table_rows("system.admission")
    by_decision = {}
    for r in rows:
        by_decision.setdefault(r["decision"], []).append(r)
    assert by_decision.get("admit") and by_decision.get("queue")
    assert by_decision["shed"][0]["reason"] == "queue-full"
    assert by_decision["shed"][0]["retry_after_seconds"] > 0
    # trace events fired (flight recorder is on by default)
    from ballista_tpu.observability import tracing

    names = {r.get("name") for r in tracing.ring_records()}
    assert "admission.queue" in names and "admission.shed" in names
    svc.close_health()


# ---------------------------------------------------------------------------
# (c) client contract: retry-after honored, retry can be disabled
# ---------------------------------------------------------------------------


def test_client_honors_retry_after_on_gate_shed(tmp_path, faults_env):
    """A shed submission (here: an injected admission-gate fault)
    surfaces as a structured retryable error; remote_collect sleeps the
    server's retry-after and resubmits within the job timeout — the
    query completes byte-identical."""
    path = _write_tbl(tmp_path)
    cluster = LocalCluster(num_executors=2, concurrent_tasks=2)
    try:
        ctx = BallistaContext("remote", "localhost", cluster.port,
                              settings={"job.timeout": "60"})
        ctx.register_tbl("t", path, TSCHEMA)
        faults_env("scheduler.admit=fail-once")
        t0 = time.time()
        out = ctx.sql(GROUPBY_SQL).collect()
        elapsed = time.time() - t0
        _assert_identical(out, _expected())
        # the armed fault genuinely fired and the client genuinely
        # waited its retry-after before resubmitting
        assert faults_mod._rules["scheduler.admit"].hits >= 1
        assert elapsed >= 0.9
        assert cluster.service.admission.sheds_total == 1
    finally:
        faults_env("")
        cluster.shutdown()


def test_client_retry_disabled_raises_immediately(tmp_path, faults_env,
                                                  monkeypatch):
    monkeypatch.setenv("BALLISTA_ADMISSION_RETRY", "off")
    path = _write_tbl(tmp_path)
    cluster = LocalCluster(num_executors=1, concurrent_tasks=1)
    try:
        ctx = BallistaContext("remote", "localhost", cluster.port,
                              settings={"job.timeout": "30"})
        ctx.register_tbl("t", path, TSCHEMA)
        faults_env("scheduler.admit=fail-once")
        with pytest.raises(AdmissionRejected) as ei:
            ctx.sql(GROUPBY_SQL).collect()
        assert ei.value.retry_after_secs > 0
        assert ei.value.reason == "admission-fault"
    finally:
        faults_env("")
        cluster.shutdown()


def test_drain_sheds_new_while_admitted_work_finishes(tmp_path,
                                                      faults_env,
                                                      monkeypatch):
    monkeypatch.setenv("BALLISTA_ADMISSION_RETRY", "off")
    path = _write_tbl(tmp_path)
    cluster = LocalCluster(num_executors=2, concurrent_tasks=2)
    try:
        ctx = BallistaContext("remote", "localhost", cluster.port,
                              settings={"job.timeout": "60"})
        ctx.register_tbl("t", path, TSCHEMA)
        faults_env("executor.task.start=delay:400")
        box = {}

        def run():
            try:
                box["out"] = ctx.sql(GROUPBY_SQL).collect()
            except BaseException as e:  # noqa: BLE001 - captured
                box["err"] = e

        th = threading.Thread(target=run)
        th.start()
        _wait_until(lambda: any(e._task_tokens
                                for e in cluster.executors), 10,
                    "job never started")
        cluster.service.begin_drain()
        # new work is rejected with the structured draining shed...
        ctx2 = BallistaContext("remote", "localhost", cluster.port,
                               settings={"job.timeout": "30"})
        ctx2.register_tbl("t", path, TSCHEMA)
        with pytest.raises(AdmissionRejected) as ei:
            ctx2.sql(GROUPBY_SQL).collect()
        assert ei.value.reason == "draining"
        # ...while the admitted job finishes byte-identical
        th.join(45)
        assert not th.is_alive(), "admitted job hung through drain"
        assert "err" not in box, f"admitted job failed: {box.get('err')}"
        _assert_identical(box["out"], _expected())
    finally:
        faults_env("")
        cluster.shutdown()


# ---------------------------------------------------------------------------
# (d) THE overload gate: K sessions x 3x capacity, bounds held, faults
# ---------------------------------------------------------------------------

# seed -> BALLISTA_FAULTS spec armed during the storm. Outcome law:
# every submission either completes byte-identical to the unloaded run
# or surfaces a structured retryable AdmissionRejected; configured
# bounds hold THROUGHOUT (sampled continuously); zero hangs.
OVERLOAD_SEEDS = {
    "baseline": "",
    "admit-fault": "scheduler.admit=fail-once:3",
    "queue-pump-fault": "scheduler.admission_queue=fail-once:2",
    "queue-pump-delay": "scheduler.admission_queue=delay:40",
}


@pytest.mark.parametrize("seed", sorted(OVERLOAD_SEEDS))
def test_overload_sweep_bounds_and_byte_identity(tmp_path, faults_env,
                                                 seed):
    path = _write_tbl(tmp_path)
    # capacity: 2 executors x 1 slot = 2 concurrent tasks
    cluster = LocalCluster(num_executors=2, concurrent_tasks=1)
    max_running = 2
    try:
        # unloaded control run on the SAME cluster (also warms jit)
        ctx0 = BallistaContext("remote", "localhost", cluster.port,
                               settings={"job.timeout": "60"})
        ctx0.register_tbl("t", path, TSCHEMA)
        expected = ctx0.sql(GROUPBY_SQL).collect()
        _assert_identical(expected, _expected())

        faults_env(OVERLOAD_SEEDS[seed])
        # continuous bound sampler: admitted concurrency and queue
        # depth must respect the configured bounds at every instant
        stop = threading.Event()
        observed = {"max_active": 0, "max_queue": 0, "violations": []}

        def sample():
            svc = cluster.service
            while not stop.is_set():
                active = len(svc.admission._active_session)
                depth = svc.admission.queue_depth()
                observed["max_active"] = max(observed["max_active"],
                                             active)
                observed["max_queue"] = max(observed["max_queue"], depth)
                if active > max_running:
                    observed["violations"].append(("active", active))
                time.sleep(0.01)

        sampler = threading.Thread(target=sample, daemon=True)
        sampler.start()

        # 3 sessions x 2 queries = 6 concurrent submissions = 3x the
        # 2-slot capacity; per-session quota 1, global cap max_running
        results = {}

        def run(session: int, q: int):
            settings = {
                "job.timeout": "90",
                "session.id": f"overload-{seed}-{session}",
                "admission.max_session_jobs": "1",
                "admission.max_running_jobs": str(max_running),
            }
            ctx = BallistaContext("remote", "localhost", cluster.port,
                                  settings=settings)
            ctx.register_tbl("t", path, TSCHEMA)
            try:
                results[(session, q)] = ctx.sql(GROUPBY_SQL).collect()
            except BaseException as e:  # noqa: BLE001 - captured
                results[(session, q)] = e

        threads = [threading.Thread(target=run, args=(s, q))
                   for s in range(3) for q in range(2)]
        t0 = time.time()
        for th in threads:
            th.start()
        for th in threads:
            th.join(120)
        stop.set()
        sampler.join(2)
        hung = [th for th in threads if th.is_alive()]
        assert not hung, \
            f"seed {seed}: {len(hung)} submissions HUNG after " \
            f"{time.time() - t0:.0f}s"

        completions = 0
        for key, out in sorted(results.items()):
            if isinstance(out, pd.DataFrame):
                _assert_identical(out, expected)
                completions += 1
            else:
                # the only acceptable error is the structured
                # retryable shed
                assert isinstance(out, AdmissionRejected), \
                    f"seed {seed} {key}: dirty failure " \
                    f"{type(out).__name__}: {out}"
                assert out.retry_after_secs > 0
        assert completions >= 4, \
            f"seed {seed}: only {completions}/6 completed"
        assert not observed["violations"], observed["violations"]
        assert observed["max_active"] <= max_running
        assert observed["max_queue"] <= 64
        # quiesced: no leaked queue entries or session slots
        assert cluster.service.admission.queue_depth() == 0
        _wait_until(
            lambda: not cluster.service.admission._active_session, 10,
            "admitted-job bookkeeping never drained")
    finally:
        faults_env("")
        cluster.shutdown()


def test_overload_queue_full_sheds_are_structured(tmp_path,
                                                  monkeypatch):
    """With a 1-deep queue and retry disabled, the overflow submission
    of a 3-burst single-session storm is shed queue-full; the other two
    complete byte-identical."""
    monkeypatch.setenv("BALLISTA_ADMISSION_RETRY", "off")
    path = _write_tbl(tmp_path)
    cluster = LocalCluster(num_executors=2, concurrent_tasks=1)
    try:
        settings = {
            "job.timeout": "60",
            "session.id": "storm-sess",
            "admission.max_session_jobs": "1",
            "admission.max_queue_depth": "1",
        }
        ctx0 = BallistaContext("remote", "localhost", cluster.port,
                               settings={"job.timeout": "60"})
        ctx0.register_tbl("t", path, TSCHEMA)
        expected = ctx0.sql(GROUPBY_SQL).collect()

        results = {}

        def run(i):
            ctx = BallistaContext("remote", "localhost", cluster.port,
                                  settings=dict(settings))
            ctx.register_tbl("t", path, TSCHEMA)
            try:
                results[i] = ctx.sql(GROUPBY_SQL).collect()
            except BaseException as e:  # noqa: BLE001 - captured
                results[i] = e
        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(3)]
        for th in threads:
            th.start()
            time.sleep(0.05)  # deterministic arrival order
        for th in threads:
            th.join(90)
        assert all(not th.is_alive() for th in threads), "storm hung"
        sheds = [r for r in results.values()
                 if isinstance(r, AdmissionRejected)]
        oks = [r for r in results.values()
               if isinstance(r, pd.DataFrame)]
        assert len(sheds) == 1 and len(oks) == 2, results
        assert sheds[0].reason == "queue-full"
        assert sheds[0].retry_after_secs > 0
        for out in oks:
            _assert_identical(out, expected)
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# (e) satellite: rate-based speculation off the live progress samples
# ---------------------------------------------------------------------------


def _running_job(state, n_tasks=2, started_ago=5.0):
    state.save_job_status("j1", JobStatus("running"))
    state.save_stage_plan("j1", 1, b"", n_tasks, [])
    now = time.time()
    for p in range(n_tasks):
        state.save_task_status(TaskStatus(
            PartitionId("j1", 1, p), "running", executor_id=f"e{p}",
            started_at=now - started_ago))


def _report(tracker, pid, rows, elapsed):
    tracker.record_report("j1", 1, pid, {
        "rows_so_far": rows, "input_rows_total": 10000,
        "bytes_so_far": 0, "elapsed_seconds": elapsed,
        "operator": "Scan", "stage_version": 0})


def test_speculation_rate_trigger_beats_age():
    """A task whose sampled rate trails the stage median by the lag
    factor is duplicated BEFORE the wall-clock age trigger would fire
    (ROADMAP 5a: the scheduler consumes the progress model)."""
    state = SchedulerState(MemoryBackend())
    tracker = JobProgressTracker(state=state)
    tracker.register_job("j1")
    _running_job(state, n_tasks=3, started_ago=5.0)  # well under age 60
    _report(tracker, 0, rows=20, elapsed=5.0)    # 4 rows/s: straggler
    _report(tracker, 1, rows=2000, elapsed=5.0)  # 400 rows/s
    _report(tracker, 2, rows=1800, elapsed=5.0)  # 360 rows/s
    assert tracker.is_lagging("j1", 1, 0) is True
    assert tracker.is_lagging("j1", 1, 1) is False
    pid = state.speculative_task(age_secs=60.0, executor_id="other",
                                 min_interval_secs=0.0,
                                 lag_fn=tracker.speculation_lag_fn())
    assert pid == PartitionId("j1", 1, 0)
    # at most one duplicate per task; its healthy siblings are NOT
    # speculated even when old (a measured not-lagging verdict wins
    # over the age trigger)
    for t in state.get_task_statuses("j1", 1):
        t.started_at = time.time() - 120.0
        state.save_task_status(t)
    assert state.speculative_task(age_secs=60.0, executor_id="other",
                                  min_interval_secs=0.0,
                                  lag_fn=tracker.speculation_lag_fn()) \
        is None


def test_speculation_age_fallback_without_samples():
    """No samples (progress plane off / task outran the heartbeat):
    the old wall-clock age trigger still speculates."""
    state = SchedulerState(MemoryBackend())
    tracker = JobProgressTracker(state=state)
    tracker.register_job("j1")
    _running_job(state, n_tasks=2, started_ago=120.0)
    pid = state.speculative_task(age_secs=60.0, executor_id="other",
                                 min_interval_secs=0.0,
                                 lag_fn=tracker.speculation_lag_fn())
    assert pid is not None
    # and a young task with no samples is left alone
    state2 = SchedulerState(MemoryBackend())
    _running_job(state2, n_tasks=2, started_ago=5.0)
    assert state2.speculative_task(age_secs=60.0, executor_id="other",
                                   min_interval_secs=0.0,
                                   lag_fn=None) is None


def test_speculation_lag_factor_knob(monkeypatch):
    from ballista_tpu.observability.progress import \
        speculation_lag_factor

    assert speculation_lag_factor() == 3.0
    monkeypatch.setenv("BALLISTA_SPECULATION_LAG_FACTOR", "10")
    assert speculation_lag_factor() == 10.0
    monkeypatch.setenv("BALLISTA_SPECULATION_LAG_FACTOR", "junk")
    assert speculation_lag_factor() == 3.0
    # factor <= 1 disables the rate trigger entirely
    monkeypatch.setenv("BALLISTA_SPECULATION_LAG_FACTOR", "1")
    state = SchedulerState(MemoryBackend())
    tracker = JobProgressTracker(state=state)
    tracker.register_job("j1")
    _running_job(state, n_tasks=2, started_ago=5.0)
    _report(tracker, 0, rows=1, elapsed=5.0)
    _report(tracker, 1, rows=5000, elapsed=5.0)
    assert tracker.is_lagging("j1", 1, 0) is None


# ---------------------------------------------------------------------------
# (f) satellites: state leak purge + retry-budget knob
# ---------------------------------------------------------------------------


def test_terminal_transition_purges_speculation_and_recovery_state():
    """_speculated / _spec_failed_once / recoveries/<job> grew for the
    scheduler's lifetime before this PR; the terminal transition now
    cleans them (pinning the leak fix)."""
    state = SchedulerState(MemoryBackend())
    for jid, final in (("j1", "completed"), ("j2", "failed"),
                       ("j3", "cancelled")):
        state.save_job_status(jid, JobStatus("queued"))
        pid = PartitionId(jid, 1, 0)
        with state._lock:
            state._speculated.add(pid)
            state._spec_failed_once.add(pid)
        state._bump_recovery(jid)
        assert state._recovery_count(jid) == 1
    # an UNRELATED live job's state must survive the purges
    live_pid = PartitionId("j-live", 1, 0)
    with state._lock:
        state._speculated.add(live_pid)
        state._spec_failed_once.add(live_pid)
    state._bump_recovery("j-live")

    state.save_job_status("j1", JobStatus("completed"))
    state.save_job_status("j2", JobStatus("failed", error="boom"))
    state.cancel_job("j3", "client")
    with state._lock:
        assert state._speculated == {live_pid}
        assert state._spec_failed_once == {live_pid}
    for jid in ("j1", "j2", "j3"):
        assert state._recovery_count(jid) == 0
        assert state.kv.get(state._k("recoveries", jid)) is None
    assert state._recovery_count("j-live") == 1


def test_max_recoveries_knob(monkeypatch):
    state = SchedulerState(MemoryBackend())
    assert state.MAX_RECOVERIES_PER_JOB == 3
    monkeypatch.setenv("BALLISTA_MAX_TASK_RECOVERIES", "1")
    assert state.MAX_RECOVERIES_PER_JOB == 1
    # the budget is READ per recovery decision: one transient failure
    # recovers, the second fails the job under budget 1
    state.save_job_status("jr", JobStatus("running"))
    state.save_stage_plan("jr", 1, b"", 1, [])
    st = TaskStatus(PartitionId("jr", 1, 0), "failed",
                    error="IoError: flaky")
    assert state.recover_transient_failure(st) is True
    assert state.recover_transient_failure(st) is False
    monkeypatch.setenv("BALLISTA_MAX_TASK_RECOVERIES", "junk")
    assert state.MAX_RECOVERIES_PER_JOB == 3


def test_scheduler_binary_sigterm_drains():
    """The REAL scheduler binary's SIGTERM path: signals must be
    BLOCKED for sigwait to receive them — without the mask SIGTERM
    took the default disposition (exit -15) and the drain rung never
    ran (found driving the binary; the executor binary had the same
    latent race around its PR 9 graceful drain)."""
    import signal
    import subprocess
    import sys

    p = subprocess.Popen(
        [sys.executable, "-m", "ballista_tpu.distributed.scheduler_main",
         "--port", "0", "--flight-port", "-1", "--metrics-port", "-1"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            if "listening on" in p.stdout.readline():
                break
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=40)
    finally:
        if p.poll() is None:
            p.kill()
    assert p.returncode == 0, f"rc={p.returncode}: {out}"
    assert "draining (new submissions are shed)" in out, out


# ---------------------------------------------------------------------------
# (g) bench_serving smoke: the serving bench emits its gated fields
# ---------------------------------------------------------------------------


def test_bench_serving_emits_gated_fields(tmp_path_factory):
    """bench_serving.run_serving end-to-end on tiny data with a tiny
    mix: the JSON fields dev/check_bench_regress.py gates must be
    populated and self-consistent (a broken serving bench must fail
    here, not silently record zeros into BENCH_rNN.json)."""
    import sys

    sys.path.insert(0, REPO)
    from benchmarks.tpch import datagen
    import bench_serving

    data_dir = str(tmp_path_factory.mktemp("serving_smoke"))
    datagen.generate(data_dir, scale=0.002, num_parts=2)
    out = bench_serving.run_serving(
        data_dir, sessions=2, queries_per_session=1, executors=2,
        slots=1, max_running=2, session_quota=1, job_timeout=120.0,
        mix=("q1",))
    assert out["metric"] == "serving_qps" and out["value"] > 0
    assert out["serving_completed"] == 2
    assert out["serving_errors"] == 0
    assert out["serving_p50_seconds"] > 0
    assert out["serving_p99_seconds"] >= out["serving_p50_seconds"]
    assert out["serving_admitted"] >= 2
    assert out["serving_solo_seconds"]["q1"] > 0


# ---------------------------------------------------------------------------
# (h) overhead gate: the admission hot path costs < 5% on submissions
# ---------------------------------------------------------------------------


def test_admission_overhead_under_5pct(tmp_path):
    """Drift-cancelling gate on the hot path admission actually sits on
    (ExecuteQuery -> planned): submissions with the gate evaluating
    real (non-binding) quotas vs admission.enabled=off, interleaved
    alternating samples + medians, <5% (+2ms floor) or fail."""
    svc = _service()
    src = TblSource(_write_tbl(tmp_path, rows=8, parts=1), TSCHEMA)
    on_settings = {"session.id": "ovh", "admission.max_session_jobs":
                   "64", "admission.max_running_jobs": "64"}
    off_settings = {"session.id": "ovh", "admission.enabled": "off"}

    def cycle(settings):
        r = _submit(svc, src, settings)
        assert not r.error
        deadline = time.time() + 10
        while not svc.state.stage_ids(r.job_id):
            assert time.time() < deadline, "planning never finished"
            time.sleep(0.001)
        svc.CancelJob(pb.CancelJobParams(job_id=r.job_id))

    def sample(on: bool) -> float:
        t0 = time.perf_counter()
        for _ in range(3):
            cycle(on_settings if on else off_settings)
        return time.perf_counter() - t0

    sample(True)
    sample(False)  # settle both paths

    def measure():
        offs, ons = [], []
        for i in range(9):
            if i % 2 == 0:
                offs.append(sample(False))
                ons.append(sample(True))
            else:
                ons.append(sample(True))
                offs.append(sample(False))
        return sorted(offs)[4], sorted(ons)[4]

    try:
        for _ in range(3):
            t_off, t_on = measure()
            if t_on <= t_off * 1.05 + 2e-3:
                return
        overhead = (t_on - t_off) / t_off
        raise AssertionError(
            f"admission overhead {overhead:.1%} "
            f"(on={t_on:.4f}s off={t_off:.4f}s)")
    finally:
        svc.close_health()
