"""Data-plane hardening and IPC fidelity tests.

The reference's Flight service trusts callers inside the cluster perimeter;
our socket data plane validates network-supplied path components the same
way the native C++ server does (shuffle_server.cpp path_component_ok), and
IPC reads must keep int64/scaled-decimal values exact (no float64 detours).
"""

import os

import numpy as np
import jax.numpy as jnp
import pytest

from ballista_tpu import schema, Int64, Utf8
from ballista_tpu.columnar import Column, ColumnBatch
from ballista_tpu.errors import IoError
from ballista_tpu.io import ipc
from ballista_tpu.distributed import dataplane


# ---------------------------------------------------------------------------
# path traversal rejection
# ---------------------------------------------------------------------------


def test_path_component_ok():
    assert dataplane.path_component_ok("abc123-XY_z")
    assert not dataplane.path_component_ok("")
    assert not dataplane.path_component_ok("..")
    assert not dataplane.path_component_ok("../other")
    assert not dataplane.path_component_ok("/etc")
    assert not dataplane.path_component_ok("a/b")
    assert not dataplane.path_component_ok("a" * 129)


def test_data_plane_rejects_traversal_job_id(tmp_path):
    # plant a file OUTSIDE work_dir that a traversal would reach
    secret = tmp_path / "secret" / "1" / "0" / "data.arrow"
    secret.parent.mkdir(parents=True)
    secret.write_bytes(b"SECRET")
    work_dir = tmp_path / "work"
    work_dir.mkdir()

    server = dataplane.start_data_plane("localhost", 0, str(work_dir))
    try:
        with pytest.raises(IoError, match="bad job id"):
            dataplane.fetch_partition_bytes(
                "localhost", server.port, "../secret", 1, 0
            )
        # absolute path job ids are rejected too
        with pytest.raises(IoError, match="bad job id"):
            dataplane.fetch_partition_bytes(
                "localhost", server.port, str(tmp_path / "secret"), 1, 0
            )
        # and the same rule guards the shuffle fetch path
        with pytest.raises(IoError, match="bad job id"):
            dataplane.fetch_partition_bytes(
                "localhost", server.port, "../secret", 1, 0, shuffle_output=0
            )
    finally:
        server.close()


# ---------------------------------------------------------------------------
# native C++ data-plane server: protocol + hardening parity
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cpp_server_bin():
    import subprocess

    native = os.path.join(os.path.dirname(__file__), "..", "ballista_tpu",
                          "native")
    r = subprocess.run(["make", "-C", native, "shuffle_server"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return os.path.join(native, "shuffle_server")


def test_cpp_shuffle_server_protocol_parity(cpp_server_bin, tmp_path):
    """The C++ server must be a drop-in for the Python one: same wire
    protocol, same path layout, same job-id hardening."""
    import subprocess
    import time

    from ballista_tpu import schema, Int64 as I64
    from ballista_tpu.columnar import ColumnBatch

    work = tmp_path / "work"
    s = schema(("v", Int64))
    batch = ColumnBatch.from_pydict(s, {"v": [7, 8, 9]})
    ipc.write_partition(
        str(work / "jobx" / "1" / "0" / "data.arrow"), [batch])
    ipc.write_partition(
        str(work / "jobx" / "1" / "0" / "shuffle-2.arrow"), [batch])

    proc = subprocess.Popen([cpp_server_bin, "0", str(work)],
                            stdout=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()
        port = int(line.split("port")[1].split()[0])
        # partition fetch
        buf = dataplane.fetch_partition_bytes("localhost", port, "jobx", 1, 0)
        _, arrays, _, _, _ = ipc.read_partition_arrays(buf)
        assert list(arrays["v"]) == [7, 8, 9]
        # shuffle fetch
        buf = dataplane.fetch_partition_bytes("localhost", port, "jobx", 1, 0,
                                              shuffle_output=2)
        _, arrays, _, _, _ = ipc.read_partition_arrays(buf)
        assert list(arrays["v"]) == [7, 8, 9]
        # traversal hardening matches the Python server
        with pytest.raises(IoError, match="bad job id"):
            dataplane.fetch_partition_bytes("localhost", port, "../etc", 1, 0)
        with pytest.raises(IoError, match="no such|bad"):
            dataplane.fetch_partition_bytes("localhost", port, "missing", 1, 0)
    finally:
        proc.terminate()
        proc.wait(timeout=5)


# ---------------------------------------------------------------------------
# int64 fidelity through IPC with nulls present
# ---------------------------------------------------------------------------


def test_ipc_nullable_int64_exact_roundtrip(tmp_path):
    s = schema(("v", Int64))
    big = (1 << 60) + 12345  # not representable in float64
    vals = np.array([big, 7, big + 2, 0], dtype=np.int64)
    validity = np.array([True, True, True, False])
    cap = 8
    pad = np.zeros(cap - 4, dtype=np.int64)
    col = Column(
        jnp.asarray(np.concatenate([vals, pad])), Int64,
        jnp.asarray(np.concatenate([validity, np.zeros(cap - 4, bool)])),
        None,
    )
    sel = np.zeros(cap, bool)
    sel[:4] = True
    batch = ColumnBatch(s, [col], jnp.asarray(sel), jnp.asarray(np.int32(4)))

    path = str(tmp_path / "p" / "data.arrow")
    ipc.write_partition(path, [batch])
    names, arrays, nulls, dicts, kinds = ipc.read_partition_arrays(path)
    assert names == ["v"]
    got = arrays["v"]
    assert got.dtype == np.int64, f"int64 degraded to {got.dtype}"
    assert got[0] == big and got[2] == big + 2  # exact, no float rounding
    assert list(nulls["v"]) == [False, False, False, True]


# ---------------------------------------------------------------------------
# fixed-size-list decode honors a chunk slice offset
# ---------------------------------------------------------------------------


def test_fixed_size_list_decode_sliced_chunk():
    # An Arrow slice adjusts offset/length only — the flat child stays
    # whole. The decode must window the child by chunk.offset*width
    # before reshaping, or a sliced producer silently reads the wrong
    # rows (in-repo IPC files arrive unsliced; this protects direct
    # zero-copy producers).
    pa = pytest.importorskip("pyarrow")
    flat = pa.array(np.arange(24, dtype=np.int64))
    fsl = pa.FixedSizeListArray.from_arrays(flat, 4)  # 6 rows, width 4
    sliced = fsl.slice(2, 3)
    assert sliced.offset == 2  # precondition: a genuinely sliced chunk
    got = ipc.decode_fixed_size_list(sliced)
    np.testing.assert_array_equal(
        got, np.arange(8, 20, dtype=np.int64).reshape(3, 4)
    )
    # unsliced stays the identity decode
    np.testing.assert_array_equal(
        ipc.decode_fixed_size_list(fsl),
        np.arange(24, dtype=np.int64).reshape(6, 4),
    )
