"""Live query progress & per-session resource metering plane.

Pins the PR-10 acceptance gates (observability/progress.py):

- a multi-stage LocalCluster q5 job reports MONOTONE non-decreasing
  progress reaching exactly 1.0, with >= 3 intermediate samples
  visible through BOTH ``/debug/jobs/<job_id>`` and
  ``SELECT * FROM system.stages``;
- ``system.sessions`` accumulates wall seconds / shuffle bytes across
  two consecutive queries of one session;
- standalone ``collect(on_progress=)`` parity: the SAME snapshot shape
  both paths deliver (schema pin);
- in-flight queries appear in ``system.queries`` with
  ``status="running"``, executors gain ``heartbeat_age_seconds`` /
  ``stale``;
- the plane costs < 5% on warm q1 (drift-cancelling scheme, PR-1).

Byte-identical results under dropped/delayed progress reports are
pinned by the ``progress-*`` seeds of test_lifecycle's chaos sweep.
"""

import json
import os
import threading
import time
import urllib.request

import pytest

from ballista_tpu import Int64, Utf8, schema
from ballista_tpu.client import BallistaContext
from ballista_tpu.distributed.executor import LocalCluster
from ballista_tpu.distributed.state import MemoryBackend, SchedulerState
from ballista_tpu.distributed.types import PartitionId, TaskStatus
from ballista_tpu.observability import progress as obs_progress
from ballista_tpu.observability.metrics import MetricsSet
from ballista_tpu.observability.progress import (
    JOB_PROGRESS_KEYS,
    STAGE_PROGRESS_KEYS,
    JobProgressTracker,
    SessionMeter,
)

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture
def fast_interval(monkeypatch):
    monkeypatch.setenv("BALLISTA_PROGRESS_INTERVAL_SECS", "0.05")


def _http_json(port: int, path: str):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return json.loads(r.read())


def _assert_snapshot_shape(snap: dict):
    assert set(snap.keys()) == set(JOB_PROGRESS_KEYS), snap.keys()
    for st in snap["stages"]:
        assert set(st.keys()) == set(STAGE_PROGRESS_KEYS), st.keys()


# ---------------------------------------------------------------------------
# units
# ---------------------------------------------------------------------------


def test_snapshot_rows_is_nonblocking_and_monotone():
    m = MetricsSet()
    assert m.snapshot_rows() == 0
    m._counters["output_rows"] = 10
    m._pending_rows.extend([3, 4])  # host ints: always "ready"
    assert m.snapshot_rows() == 17
    # non-destructive: values() still owns the real accounting
    assert m.snapshot_rows() == 17
    assert m.values()["output_rows"] == 17
    assert m.snapshot_rows() == 17  # resolved into the counter now


def test_progress_interval_knob(monkeypatch):
    monkeypatch.setenv("BALLISTA_PROGRESS_INTERVAL_SECS", "off")
    assert obs_progress.progress_interval_secs() is None
    monkeypatch.setenv("BALLISTA_PROGRESS_INTERVAL_SECS", "0")
    assert obs_progress.progress_interval_secs() is None
    monkeypatch.setenv("BALLISTA_PROGRESS_INTERVAL_SECS", "2.5")
    assert obs_progress.progress_interval_secs() == 2.5
    monkeypatch.setenv("BALLISTA_PROGRESS_INTERVAL_SECS", "bogus")
    assert obs_progress.progress_interval_secs() == 1.0


def test_tracker_folds_samples_and_clamps_monotone():
    state = SchedulerState(MemoryBackend())
    state.save_stage_plan("j1", 1, b"", 2, [])
    for p in range(2):
        state.save_task_status(TaskStatus(PartitionId("j1", 1, p)))
    tr = JobProgressTracker(state=state)
    tr.register_job("j1")
    snap = tr.snapshot("j1")
    _assert_snapshot_shape(snap)
    assert snap["fraction"] == 0.0 and snap["tasks_total"] == 2
    # one task starts running and reports half its input consumed
    state.save_task_status(TaskStatus(PartitionId("j1", 1, 0), "running",
                                      executor_id="e1",
                                      started_at=time.time()))
    tr.record_report("j1", 1, 0, {"rows_so_far": 50,
                                  "input_rows_total": 100,
                                  "bytes_so_far": 10,
                                  "operator": "ScanExec"})
    snap = tr.snapshot("j1")
    assert 0.2 < snap["fraction"] <= 0.25  # 0.5 of 1 of 2 tasks
    assert snap["tasks_running"] == 1 and snap["tasks_queued"] == 1
    assert snap["stages"][0]["rows_so_far"] == 50
    # a later, WORSE sample must not move the job fraction backwards
    tr.record_report("j1", 1, 0, {"rows_so_far": 10,
                                  "input_rows_total": 100,
                                  "bytes_so_far": 10, "operator": ""})
    snap2 = tr.snapshot("j1")
    assert snap2["fraction"] >= snap["fraction"]
    # a running task's partial is capped below 1.0 even when the
    # estimate undershoots reality
    tr.record_report("j1", 1, 0, {"rows_so_far": 500,
                                  "input_rows_total": 100,
                                  "bytes_so_far": 10, "operator": ""})
    assert tr.snapshot("j1")["fraction"] < 0.5
    # completion: both tasks done -> finish freezes exactly 1.0
    for p in range(2):
        state.save_task_status(TaskStatus(
            PartitionId("j1", 1, p), "completed", executor_id="e1",
            stats={"num_rows": 100, "num_bytes": 7}))
    from ballista_tpu.distributed.types import JobStatus

    state.save_job_status("j1", JobStatus("completed"))
    tr.finish("j1", "completed")
    final = tr.snapshot("j1")
    assert final["fraction"] == 1.0
    assert final["status"] == "completed"
    assert final["eta_seconds"] == 0.0
    assert final["tasks_completed"] == 2
    # system.tasks only lists running tasks -> empty now
    assert tr.task_rows() == []
    assert tr.stage_rows() == []  # terminal jobs leave the live tables


def test_session_meter_accumulates_and_survives_restart(tmp_path):
    d = str(tmp_path / "log")
    m = SessionMeter(d)
    m.record("s1", wall_seconds=1.5, task_seconds=2.0,
             bytes_shuffled=100, peak_host_bytes=50)
    m.record("s1", wall_seconds=0.5, bytes_shuffled=10,
             peak_host_bytes=20)
    rows = m.rows()
    assert len(rows) == 1
    r = rows[0]
    assert r["queries"] == 2
    assert r["wall_seconds"] == 2.0
    assert r["bytes_shuffled"] == 110
    assert r["peak_host_bytes"] == 50  # max, not sum
    m.annotate("s1", device_blocked_seconds=0.25)
    # disk writes are debounced off the hot path — flush() (what the
    # atexit hook runs) makes the pending updates durable NOW
    m.flush()
    # a fresh meter over the same directory resumes the accounting
    m2 = SessionMeter(d)
    r2 = m2.rows()[0]
    assert r2["queries"] == 2 and r2["device_blocked_seconds"] == 0.25


# ---------------------------------------------------------------------------
# standalone parity
# ---------------------------------------------------------------------------


def _slow_ctx(rows: int = 6000, parts: int = 4, delay: float = 0.12):
    from ballista_tpu.io.memory import MemTableSource

    class Slow(MemTableSource):
        def scan(self, p, projection=None):
            time.sleep(delay)
            return super().scan(p, projection)

    inner = MemTableSource.from_pydict(
        schema(("a", Int64), ("c", Utf8)),
        {"a": list(range(rows)), "c": [f"k{i % 7}" for i in range(rows)]},
        num_partitions=parts,
    )
    ctx = BallistaContext.standalone()
    ctx.register_source("t", Slow(inner._schema, inner._partitions))
    return ctx


def test_standalone_on_progress_monotone_and_shaped(fast_interval):
    ctx = _slow_ctx()
    samples = []
    out = ctx.sql("select c, sum(a) as s from t group by c "
                  "order by c").collect(on_progress=samples.append)
    assert len(out) == 7
    assert samples, "sampler delivered nothing"
    for s in samples:
        _assert_snapshot_shape(s)
    fractions = [s["fraction"] for s in samples]
    assert fractions == sorted(fractions), fractions
    assert fractions[-1] == 1.0
    assert samples[-1]["status"] == "completed"
    assert samples[-1]["stages"][0]["tasks_completed"] == 1
    # same session id accounted for the query
    rows = {r["session_id"]
            for r in obs_progress.process_session_meter().rows()}
    assert ctx.session_id in rows


def test_standalone_live_surfaces_while_in_flight(fast_interval):
    ctx = _slow_ctx(parts=4, delay=0.25)
    box = {}

    def run():
        try:
            box["out"] = ctx.sql(
                "select sum(a) as s from t").collect()
        except BaseException as e:  # noqa: BLE001
            box["err"] = e

    th = threading.Thread(target=run)
    th.start()
    try:
        deadline = time.time() + 5
        live_seen = tasks_seen = stages_seen = False
        probe = BallistaContext.standalone()
        while time.time() < deadline and not (
                live_seen and tasks_seen and stages_seen):
            recs = [r for r in obs_progress.local_live_query_records()
                    if r["job_id"].startswith("local-")]
            live_seen = live_seen or any(
                r["status"] == "running" and r["wall_seconds"] >= 0
                for r in recs)
            tasks_seen = tasks_seen or bool(
                probe.sql("select * from system.tasks").collect()
                .to_dict("records"))
            stages_seen = stages_seen or bool(
                obs_progress.local_stage_rows())
            time.sleep(0.05)
    finally:
        th.join()
    assert "err" not in box, box.get("err")
    assert live_seen and tasks_seen and stages_seen
    # ctx.job_progress on the standalone path: nothing in flight now
    assert ctx.job_progress("not-a-job") is None


# ---------------------------------------------------------------------------
# cluster acceptance gate: multi-stage q5 with live surfaces
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tpch_small(tmp_path_factory):
    from benchmarks.tpch import datagen

    data_dir = str(tmp_path_factory.mktemp("tpch_prog"))
    datagen.generate(data_dir, scale=0.01, num_parts=2)
    return data_dir


def test_cluster_q5_progress_gate(tpch_small, fast_interval,
                                  monkeypatch):
    """THE acceptance gate: a LocalCluster q5 job reports monotone
    non-decreasing progress reaching exactly 1.0, with >= 3
    intermediate samples observed via /debug/jobs/<job_id> AND via
    ``SELECT * FROM system.stages``; system.sessions accumulates
    across two consecutive queries of the session. Tasks are slowed by
    a deterministic fault delay so the live surfaces have a real
    window to observe — results are unaffected (delay is advisory to
    progress, invisible to semantics)."""
    from benchmarks.tpch.schema_def import register_tpch
    from ballista_tpu.testing.faults import reload_faults

    monkeypatch.setenv("BALLISTA_FAULTS",
                       "executor.task.start=delay:350")
    reload_faults()
    cluster = LocalCluster(num_executors=2, concurrent_tasks=2,
                           metrics_port=0)
    try:
        ctx = BallistaContext.remote("localhost", cluster.port,
                                     **{"job.timeout": "180"})
        register_tpch(ctx, tpch_small, "tbl")
        sql = open(os.path.join(REPO, "benchmarks", "tpch", "queries",
                                "q5.sql")).read()
        sport = cluster.scheduler_health_port
        samples: list = []
        debug_snaps: list = []
        stage_scans: list = []
        stop = threading.Event()
        ctx2 = BallistaContext.remote("localhost", cluster.port,
                                      **{"job.timeout": "60"})

        def poll():
            # /debug/jobs/<id> at a tight cadence; SELECTs are full
            # cluster queries, so they run as fast as they run
            while not stop.is_set():
                jid = samples[0]["job_id"] if samples else None
                if jid:
                    try:
                        debug_snaps.append(
                            _http_json(sport, f"/debug/jobs/{jid}"))
                    except Exception:  # noqa: BLE001
                        pass
                    try:
                        rows = ctx2.sql(
                            "select * from system.stages").collect() \
                            .to_dict("records")
                        stage_scans.append(
                            [r for r in rows if r["job_id"] == jid])
                    except Exception:  # noqa: BLE001
                        pass
                stop.wait(0.05)

        th = threading.Thread(target=poll)
        th.start()
        try:
            out = ctx.sql(sql).collect(on_progress=samples.append)
        finally:
            stop.set()
            th.join()
        assert len(out) > 0
        jid = samples[0]["job_id"]

        # client callbacks: monotone, terminal exactly 1.0, the shape
        for s in samples:
            _assert_snapshot_shape(s)
        fractions = [s["fraction"] for s in samples]
        assert fractions == sorted(fractions), fractions
        assert fractions[-1] == 1.0
        intermediate = [f for f in fractions if 0.0 < f < 1.0]
        assert len(set(intermediate)) >= 3, fractions

        # /debug/jobs/<job_id>: >= 3 intermediate samples, monotone
        dfr = [d["fraction"] for d in debug_snaps]
        assert dfr == sorted(dfr), dfr
        assert len({f for f in dfr if 0.0 < f < 1.0}) >= 3, dfr
        _assert_snapshot_shape(debug_snaps[0])  # /debug/jobs shape pin
        assert any(d["tasks_running"] > 0 for d in debug_snaps)
        # multi-stage: the job decomposes into > 1 stage
        assert len(debug_snaps[-1]["stages"]) > 1

        # SELECT * FROM system.stages saw the job mid-flight >= 3 times
        live_scans = [rows for rows in stage_scans
                      if rows and any(r["fraction"] < 1.0 for r in rows)]
        assert len(live_scans) >= 3, \
            f"{len(stage_scans)} scans, {len(live_scans)} live"

        # terminal snapshot served after completion: exactly 1.0
        final = _http_json(sport, f"/debug/jobs/{jid}")
        assert final["fraction"] == 1.0
        assert final["status"] == "completed"

        # session metering across two consecutive queries
        sess = ctx.sql("select * from system.sessions").collect()
        row = sess[sess.session_id == ctx.session_id].iloc[0]
        assert int(row.queries) >= 1
        assert int(row.bytes_shuffled) > 0
        w1, q1 = float(row.wall_seconds), int(row.queries)
        ctx.sql("select count(*) as n from lineitem").collect()
        sess2 = ctx.sql("select * from system.sessions").collect()
        row2 = sess2[sess2.session_id == ctx.session_id].iloc[0]
        assert int(row2.queries) > q1
        assert float(row2.wall_seconds) > w1
        assert int(row2.bytes_shuffled) >= int(row.bytes_shuffled)

        # in-flight rows are gone; the terminal record stands
        dbg = _http_json(sport, "/debug/queries")
        states = {q.get("job_id"): q.get("status") for q in dbg["queries"]}
        assert states.get(jid) == "completed"

        # executors: fresh heartbeats, stale=0
        ex = ctx.sql("select executor_id, heartbeat_age_seconds, stale "
                     "from system.executors").collect()
        assert len(ex) >= 2
        assert set(ex.stale) == {0}, ex
    finally:
        monkeypatch.delenv("BALLISTA_FAULTS", raising=False)
        reload_faults()
        cluster.shutdown()


def test_in_flight_cluster_queries_and_stale_executors(tmp_path,
                                                       fast_interval):
    """/debug/queries + system.queries carry status="running" rows for
    in-flight cluster jobs; a stopped executor's system.executors row
    flips stale=true once its heartbeat ages past the knob."""
    d = tmp_path / "t"
    d.mkdir()
    for part in range(2):
        (d / f"p{part}.tbl").write_text(
            "\n".join(f"{i}|k{i % 5}|" for i in range(30000)
                      if i % 2 == part) + "\n")
    cluster = LocalCluster(num_executors=2, concurrent_tasks=2,
                           metrics_port=0)
    try:
        ctx = BallistaContext.remote("localhost", cluster.port,
                                     **{"job.timeout": "60"})
        ctx.register_tbl("t", str(d), schema(("a", Int64), ("c", Utf8)))
        box = {}
        th = threading.Thread(target=lambda: box.update(
            out=ctx.sql("select c, sum(a) as s from t group by c"
                        ).collect()))
        th.start()
        running = []
        deadline = time.time() + 10
        svc = cluster.service
        while time.time() < deadline and not running and th.is_alive():
            rows = svc.systables.table_rows("system.queries")
            running = [r for r in rows
                       if r.get("status") in ("running", "queued")]
            time.sleep(0.02)
        th.join()
        assert "out" in box
        assert running, "no in-flight system.queries row observed"
        # terminal record replaced the live row
        rows = svc.systables.table_rows("system.queries")
        by_job = {r["job_id"]: r for r in rows}
        assert by_job[running[0]["job_id"]]["status"] in (
            "completed",)
        # staleness: stop one executor, shrink the knob, re-scan
        stopped = cluster.executors[0]
        stopped.stop()
        # threshold must exceed the 0.25s poll interval (a LIVE
        # executor's age oscillates within one poll period)
        os.environ["BALLISTA_EXECUTOR_STALE_SECS"] = "1.0"
        try:
            time.sleep(1.4)
            ex = {r["executor_id"]: r
                  for r in svc.systables.table_rows("system.executors")}
            assert ex[stopped.id]["stale"] == 1, ex[stopped.id]
            assert ex[stopped.id]["heartbeat_age_seconds"] > 1.0
            live_id = cluster.executors[1].id
            assert ex[live_id]["stale"] == 0, ex[live_id]
            assert ex[live_id]["heartbeat_age_seconds"] < 1.0
        finally:
            os.environ.pop("BALLISTA_EXECUTOR_STALE_SECS", None)
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# overhead gate: the plane costs < 5% on warm q1 (drift-cancelling)
# ---------------------------------------------------------------------------


def test_progress_overhead_q1_under_5pct(tmp_path_factory, monkeypatch):
    """PR-1's drift-cancelling scheme: warm q1 WITH an on_progress
    sampler at the tight interval vs the same collect without one.
    Interleaved alternating samples + medians cancel machine drift;
    < 5% (+2ms floor) or fail."""
    from benchmarks.tpch import datagen
    from benchmarks.tpch.schema_def import register_tpch

    monkeypatch.setenv("BALLISTA_PROGRESS_INTERVAL_SECS", "0.05")
    data_dir = str(tmp_path_factory.mktemp("tpch_prog_ovh"))
    datagen.generate(data_dir, scale=0.01, num_parts=1)
    ctx = BallistaContext.standalone()
    register_tpch(ctx, data_dir, "tbl")
    qdir = os.path.join(REPO, "benchmarks", "tpch", "queries")
    df = ctx.sql(open(os.path.join(qdir, "q1.sql")).read())
    df.collect()  # warm: jit compile + table caches
    plan, phys = df.plan, df._phys
    sink = []

    def sample(on: bool) -> float:
        t0 = time.perf_counter()
        for _ in range(3):
            ctx._standalone_collect(
                plan, phys, on_progress=sink.append if on else None)
        return time.perf_counter() - t0

    sample(True)
    sample(False)  # settle both paths before measuring

    def measure():
        offs, ons = [], []
        for i in range(9):
            if i % 2 == 0:
                offs.append(sample(False))
                ons.append(sample(True))
            else:
                ons.append(sample(True))
                offs.append(sample(False))
        return sorted(offs)[4], sorted(ons)[4]

    for _ in range(3):
        t_off, t_on = measure()
        if t_on <= t_off * 1.05 + 2e-3:
            assert sink, "the measured sampler never fired"
            return
    overhead = (t_on - t_off) / t_off
    raise AssertionError(
        f"progress overhead {overhead:.1%} "
        f"(on={t_on:.4f}s off={t_off:.4f}s)")
