"""Shuffle-loss recovery: an executor dies after producing stage output;
the job must still complete.

The reference detects failures but never recovers (any failed task fails
the job, reference: rust/scheduler/src/state/mod.rs:342-346; leases at
:42,89 only age dead executors out of metadata). Here a tagged
ShuffleFetchError makes the scheduler reset + re-queue the lost producer
partitions, and lease-expired executors' running tasks are reaped.

Style: direct service calls + manually pumped executors (no poll-loop
timing), like the reference's tonic-without-network tests
(rust/scheduler/src/lib.rs:444-491)."""

import shutil
import time

import numpy as np
import pytest

from ballista_tpu import schema, col, sum_, Int64, Utf8, serde
from ballista_tpu.distributed.executor import Executor, ExecutorConfig
from ballista_tpu.distributed.scheduler import SchedulerService
from ballista_tpu.distributed.state import (
    EXECUTOR_LEASE_SECS,
    MemoryBackend,
    SchedulerState,
)
from ballista_tpu.distributed.types import PartitionId, TaskStatus
from ballista_tpu.errors import ShuffleFetchError
from ballista_tpu.logical import LogicalPlanBuilder
from ballista_tpu.proto import ballista_pb2 as pb


def _source(tmp_path):
    # two partition files -> a 2-task producer stage
    d = tmp_path / "t"
    d.mkdir()
    for part in range(2):
        lines = [f"{i}|k{i % 3}|" for i in range(60) if i % 2 == part]
        (d / f"part{part}.tbl").write_text("\n".join(lines) + "\n")
    from ballista_tpu.io import TblSource

    return TblSource(str(d), schema(("a", Int64), ("c", Utf8)))


def _submit_groupby(svc, src):
    plan = (
        LogicalPlanBuilder.scan("t", src)
        .aggregate([col("c")], [sum_(col("a")).alias("s")])
        .build()
    )
    params = pb.ExecuteQueryParams()
    params.logical_plan.CopyFrom(serde.plan_to_proto(plan))
    job_id = svc.ExecuteQuery(params).job_id
    deadline = time.time() + 10
    while not svc.state.stage_ids(job_id):
        assert time.time() < deadline, "planning never finished"
        time.sleep(0.05)
    # stage plans persist BEFORE the ready queue is seeded (enqueue_job
    # runs last in the planning thread); wait until tasks are actually
    # dispatchable or the first manual _pump races planning under load
    while not svc.state._ready:
        assert time.time() < deadline, "job never enqueued"
        time.sleep(0.05)
    return job_id


def _pump(svc, executor, run=True):
    """One manual poll cycle: report pending statuses, maybe run a task.
    Returns the PartitionId it ran (or None)."""
    params = pb.PollWorkParams(can_accept_task=run)
    params.metadata.id = executor.id
    params.metadata.host = executor.config.host
    params.metadata.port = executor.port
    params.metadata.num_devices = 1
    with executor._status_lock:
        for st in executor._pending_status:
            params.task_status.append(st)
        executor._pending_status.clear()
    result = svc.PollWork(params)
    if not (run and result.HasField("task")):
        return None
    td = result.task
    pid = PartitionId(td.task_id.job_id, td.task_id.stage_id,
                      td.task_id.partition_id)
    plan = serde.physical_from_proto(td.plan)
    shuffle = None
    if td.shuffle_output_partitions:
        hx = [serde.expr_from_proto(e) for e in td.shuffle_hash_exprs]
        shuffle = (hx or None, td.shuffle_output_partitions)
    try:
        stats = executor.execute_partition(pid, plan, shuffle)
        executor._report_completed(pid, stats)
    except Exception as e:  # noqa: BLE001 - report like the real loop
        executor._report_failed(pid, str(e))
    return pid


def _make_executor(tmp_path, name):
    return Executor(ExecutorConfig(
        work_dir=str(tmp_path / name), scheduler_port=1,
    ))


def test_job_survives_producer_executor_death(tmp_path):
    svc = SchedulerService(SchedulerState(MemoryBackend()))
    e1 = _make_executor(tmp_path, "e1")
    e2 = _make_executor(tmp_path, "e2")
    try:
        job_id = _submit_groupby(svc, _source(tmp_path))

        # e1 runs the whole producer (partial-aggregate) stage
        ran = [_pump(svc, e1), _pump(svc, e1)]
        assert all(r is not None for r in ran)
        _pump(svc, e1, run=False)  # report completions
        assert svc.state.get_job_status(job_id).state != "failed"

        # e1 dies: its shuffle files and data plane are gone
        e1._data_plane.close()
        shutil.rmtree(e1.config.work_dir)

        # e2 picks up the final stage, fails to fetch, reports the tagged
        # error; the scheduler re-queues the lost producer partitions
        pid = _pump(svc, e2)
        assert pid is not None and pid.stage_id != ran[0].stage_id
        _pump(svc, e2, run=False)
        st = svc.state.get_job_status(job_id)
        assert st.state != "failed", f"job failed instead of recovering: {st.error}"

        # e2 re-runs the producers and then the final stage to completion
        for _ in range(8):
            _pump(svc, e2)
            if svc.state.get_job_status(job_id).state == "completed":
                break
        status = svc.state.get_job_status(job_id)
        assert status.state == "completed", (status.state, status.error)

        # result correctness: read the final partition via the data plane
        from ballista_tpu.distributed.dataplane import fetch_partition_bytes
        from ballista_tpu.io import ipc

        locs = status.partition_locations
        got = {}
        for loc in locs:
            buf = fetch_partition_bytes("localhost", e2.port, loc.job_id,
                                        loc.stage_id, loc.partition_id)
            names, arrays, _, dicts, _ = ipc.read_partition_arrays(buf)
            # the registry hands back a resolved Dictionary (raw value
            # array only with BALLISTA_DICT_REGISTRY=off)
            dvals = np.asarray(getattr(dicts["c"], "values", dicts["c"]),
                               dtype=object)
            keys = dvals[arrays["c"]]
            for k, s in zip(keys, arrays["s"]):
                got[str(k)] = got.get(str(k), 0) + int(s)
        a = np.arange(60)
        exp = {f"k{r}": int(a[a % 3 == r].sum()) for r in range(3)}
        assert got == exp
    finally:
        for e in (e1, e2):
            try:
                e._data_plane.close()
            except Exception:  # noqa: BLE001 - already dead
                pass


def test_retry_budget_exhaustion_fails_job(tmp_path):
    svc = SchedulerService(SchedulerState(MemoryBackend()))
    state = svc.state
    job_id = "j000001"
    state.save_job_status(job_id, __import__(
        "ballista_tpu.distributed.types", fromlist=["JobStatus"]
    ).JobStatus("running"))
    # a fake 1-partition producer stage, already completed
    state.save_stage_plan(job_id, 1, b"", 1, [])
    state.save_task_status(TaskStatus(PartitionId(job_id, 1, 0), "completed",
                                      executor_id="gone"))
    state.save_stage_plan(job_id, 2, b"", 1, [1])
    consumer = TaskStatus(
        PartitionId(job_id, 2, 0), "failed",
        error=str(ShuffleFetchError(1, [0], "gone", "connection refused")),
    )
    for i in range(state.MAX_RECOVERIES_PER_JOB):
        assert state.recover_fetch_failure(consumer), f"recovery {i} refused"
        # producer "completes" again on a new executor each round
        state.save_task_status(TaskStatus(PartitionId(job_id, 1, 0),
                                          "completed", executor_id="e2"))
    # budget exhausted: recovery refuses, normal failure path applies
    assert not state.recover_fetch_failure(consumer)


def test_transient_task_failure_requeued():
    """IO-shaped task failures re-queue within budget; deterministic ones
    fail fast (the reference fails the job on any failure)."""
    from ballista_tpu.distributed.types import JobStatus

    state = SchedulerState(MemoryBackend())
    state.save_job_status("j000003", JobStatus("running"))
    state.save_stage_plan("j000003", 1, b"", 1, [])
    pid = PartitionId("j000003", 1, 0)

    transient = TaskStatus(pid, "failed", error="IoError: disk hiccup")
    assert state.recover_transient_failure(transient)
    assert state.next_task() == pid
    assert state.get_task_statuses("j000003", 1)[0].state is None

    deterministic = TaskStatus(pid, "failed",
                               error="ExecutionError: capacity exceeded")
    assert not state.recover_transient_failure(deterministic)

    # budget: repeated transient failures eventually fail
    for _ in range(state.MAX_RECOVERIES_PER_JOB - 1):
        assert state.recover_transient_failure(transient)
    assert not state.recover_transient_failure(transient)


def test_shuffle_fetch_error_parse_with_class_prefix():
    e = ShuffleFetchError(3, [1, 2], "ex1", "connection refused")
    prefixed = f"{type(e).__name__}: {e}"
    assert ShuffleFetchError.parse(prefixed) == (3, [1, 2], "ex1")
    assert ShuffleFetchError.parse("ExecutionError: nope") is None


def test_speculative_execution_of_stragglers(tmp_path):
    """An idle executor gets a DUPLICATE of a long-running task (the
    reference has no speculation at all); first completion wins."""
    svc = SchedulerService(SchedulerState(MemoryBackend()),
                           speculation_age_secs=0.05)
    e1 = _make_executor(tmp_path, "e1")
    e2 = _make_executor(tmp_path, "e2")
    try:
        job_id = _submit_groupby(svc, _source(tmp_path))
        # e1 takes both producer tasks but "hangs" (never reports back):
        # poll directly so the tasks are assigned without executing
        for _ in range(2):
            params = pb.PollWorkParams(can_accept_task=True)
            params.metadata.id = e1.id
            params.metadata.host = "localhost"
            params.metadata.port = e1.port
            params.metadata.num_devices = 1
            assert svc.PollWork(params).HasField("task")
        time.sleep(0.1)  # exceed the straggler threshold

        # e2 polls: ready queue is empty, so it receives DUPLICATES of
        # e1's stuck tasks and actually runs them
        ran = [_pump(svc, e2), _pump(svc, e2)]
        assert all(r is not None for r in ran)
        for _ in range(6):
            _pump(svc, e2)
            if svc.state.get_job_status(job_id).state == "completed":
                break
        assert svc.state.get_job_status(job_id).state == "completed"
        # each task is duplicated at most once
        assert svc.state.speculative_task(age_secs=0.0) is None
    finally:
        for e in (e1, e2):
            e._data_plane.close()


def test_reap_requeues_running_tasks_of_dead_executor(tmp_path):
    from ballista_tpu.distributed.types import ExecutorMeta, JobStatus

    state = SchedulerState(MemoryBackend())
    state.save_executor_metadata(ExecutorMeta("live", "localhost", 1, 1))
    state.save_job_status("j000002", JobStatus("running"))
    state.save_stage_plan("j000002", 1, b"", 2, [])
    state.save_task_status(TaskStatus(PartitionId("j000002", 1, 0),
                                      "running", executor_id="dead"))
    state.save_task_status(TaskStatus(PartitionId("j000002", 1, 1),
                                      "running", executor_id="live"))
    state.reap_lost_tasks(min_interval_secs=0.0)
    # the dead executor's task is pending + queued again; the live one isn't
    statuses = {t.partition.partition_id: t.state
                for t in state.get_task_statuses("j000002", 1)}
    assert statuses == {0: None, 1: "running"}
    nxt = state.next_task()
    assert nxt == PartitionId("j000002", 1, 0)
    assert state.next_task() is None


def test_speculation_never_duplicates_onto_same_executor():
    """The executor already running a straggler must not receive its own
    duplicate: both copies would write the same deterministic work_dir
    path concurrently (single-executor clusters made this deterministic
    data corruption before the exclusion)."""
    from ballista_tpu.distributed.types import JobStatus

    state = SchedulerState(MemoryBackend())
    state.save_job_status("j000003", JobStatus("running"))
    state.save_stage_plan("j000003", 1, b"", 1, [])
    state.save_task_status(TaskStatus(
        PartitionId("j000003", 1, 0), "running", executor_id="e1",
        started_at=time.time() - 120,
    ))
    # e1 (the straggler's own executor) asks: no duplicate
    assert state.speculative_task(age_secs=60.0, executor_id="e1",
                                  min_interval_secs=0.0) is None
    # a different executor gets the duplicate
    assert state.speculative_task(age_secs=60.0, executor_id="e2",
                                  min_interval_secs=0.0) == \
        PartitionId("j000003", 1, 0)


def test_first_completion_wins_on_duplicate_reports():
    """A speculative duplicate and the original can both finish; the
    SECOND completion report must be dropped so consumers keep fetching
    from the recorded (first) location."""
    from ballista_tpu.distributed.types import ExecutorMeta, JobStatus

    state = SchedulerState(MemoryBackend())
    state.save_executor_metadata(ExecutorMeta("e1", "h1", 1, 1))
    state.save_executor_metadata(ExecutorMeta("e2", "h2", 2, 1))
    state.save_job_status("j000004", JobStatus("running"))
    state.save_stage_plan("j000004", 1, b"", 1, [])
    pid = PartitionId("j000004", 1, 0)
    state.task_completed(TaskStatus(pid, "completed", executor_id="e1",
                                    path="/w1/data.arrow"))
    state.task_completed(TaskStatus(pid, "completed", executor_id="e2",
                                    path="/w2/data.arrow"))
    (st,) = state.get_task_statuses("j000004", 1)
    assert st.executor_id == "e1" and st.path == "/w1/data.arrow"
    locs = state.stage_locations("j000004")[1]
    assert [(loc.host, loc.path) for loc in locs] == [("h1", "/w1/data.arrow")]


def test_unroutable_location_fails_resolution_with_tagged_error():
    """A completed task whose executor has NO address record (no lease,
    no durable record) must raise the tagged ShuffleFetchError at
    resolution time — never emit host='', port=0 for a consumer to trip
    over."""
    from ballista_tpu.distributed.types import JobStatus

    state = SchedulerState(MemoryBackend())
    state.save_job_status("j000005", JobStatus("running"))
    state.save_stage_plan("j000005", 1, b"", 1, [])
    state.save_task_status(TaskStatus(
        PartitionId("j000005", 1, 0), "completed", executor_id="gone",
        path="/lost/data.arrow",
    ))
    with pytest.raises(ShuffleFetchError) as ei:
        state.stage_locations("j000005")
    assert ei.value.stage_id == 1 and ei.value.partition_ids == [0]


def test_atomic_partition_write_leaves_no_tmp(tmp_path):
    """write_partition goes through tmp+rename so a concurrent duplicate
    writer can never expose a half-written file."""
    from ballista_tpu.columnar import ColumnBatch
    from ballista_tpu.datatypes import Int64
    from ballista_tpu.io import ipc

    batch = ColumnBatch.from_numpy(
        schema(("a", Int64)), {"a": np.arange(8, dtype=np.int64)}
    )
    path = str(tmp_path / "j" / "1" / "0" / "data.arrow")
    stats = ipc.write_partition(path, [batch])
    assert stats["num_rows"] == 8
    leftovers = [p for p in (tmp_path / "j" / "1" / "0").iterdir()
                 if p.name != "data.arrow"]
    assert leftovers == []
    # overwrite (duplicate completing later) also lands atomically
    ipc.write_partition(path, [batch])
    names, arrays, _, _, _ = ipc.read_partition_arrays(path)
    assert names == ["a"] and len(arrays["a"]) == 8


def test_failure_report_cannot_clobber_completed_task():
    """The losing speculative duplicate may FAIL after the original
    completed; that failure report must be dropped (no status clobber,
    no spurious recovery)."""
    from ballista_tpu.distributed.types import ExecutorMeta, JobStatus

    svc = SchedulerService(SchedulerState(MemoryBackend()))
    state = svc.state
    state.save_executor_metadata(ExecutorMeta("e1", "h1", 1, 1))
    state.save_job_status("j000006", JobStatus("running"))
    state.save_stage_plan("j000006", 1, b"", 1, [])
    pid = PartitionId("j000006", 1, 0)
    state.task_completed(TaskStatus(pid, "completed", executor_id="e1",
                                    path="/w1/data.arrow"))
    params = pb.PollWorkParams(can_accept_task=False)
    params.metadata.id = "e2"
    params.metadata.host = "h2"
    params.metadata.port = 2
    params.metadata.num_devices = 1
    ts = params.task_status.add()
    ts.partition_id.job_id = "j000006"
    ts.partition_id.stage_id = 1
    ts.partition_id.partition_id = 0
    ts.failed.error = "IoError: disk full on the duplicate"
    svc.PollWork(params)
    (st,) = state.get_task_statuses("j000006", 1)
    assert st.state == "completed" and st.path == "/w1/data.arrow"


def test_first_failure_of_speculated_task_is_absorbed():
    """When a task has an in-flight speculative duplicate, ONE failure
    report must not fail the job (the twin may still succeed); a second
    failure flows through the normal path."""
    from ballista_tpu.distributed.types import JobStatus

    state = SchedulerState(MemoryBackend())
    state.save_job_status("j000007", JobStatus("running"))
    state.save_stage_plan("j000007", 1, b"", 1, [])
    pid = PartitionId("j000007", 1, 0)
    state.save_task_status(TaskStatus(pid, "running", executor_id="e1",
                                      started_at=time.time() - 120))
    dup = state.speculative_task(age_secs=60.0, executor_id="e2",
                                 min_interval_secs=0.0)
    assert dup == pid
    assert state.absorb_speculative_failure(pid)      # first: absorbed
    assert not state.absorb_speculative_failure(pid)  # second: real
    # a task WITHOUT a duplicate never absorbs
    other = PartitionId("j000007", 1, 99)
    assert not state.absorb_speculative_failure(other)
