"""Warm-path serving caches (docs/caching.md): correctness contracts.

The three tiers share ONE invalidation signal — file signatures are
re-stat'd at lookup and plan fingerprints ride ``compile_signature`` —
so the contracts tested here are exactly the ones an operator relies
on: a changed file is NEVER served stale, every tier is byte-identical
on vs off (q1/q5/q16, standalone AND LocalCluster), donation never
changes results, and a starved budget degrades to plain re-ingest —
queries slow down, they do not fail.
"""

import os

import numpy as np
import pandas as pd
import pytest

from benchmarks.tpch import datagen
from benchmarks.tpch.schema_def import register_tpch

QDIR = os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                    "tpch", "queries")


def _q(qname: str) -> str:
    return open(os.path.join(QDIR, f"{qname}.sql")).read()


@pytest.fixture(scope="session")
def tpch_dir(tmp_path_factory):
    data_dir = str(tmp_path_factory.mktemp("tpch_cache"))
    datagen.generate(data_dir, scale=0.002, num_parts=2)
    return data_dir


@pytest.fixture(autouse=True)
def _fresh_tiers():
    """Every test starts and ends with empty tiers and released budget
    so fills from other tests (or other FILES in the same process)
    never leak into counters asserted here."""
    from ballista_tpu.cache import residency, results

    residency._reset_for_tests()
    results.process_result_cache().invalidate()
    yield
    residency._reset_for_tests()
    results.process_result_cache().invalidate()


def _standalone(data_dir, **settings):
    from ballista_tpu.client import BallistaContext

    ctx = BallistaContext("standalone", settings=settings or None)
    register_tpch(ctx, data_dir, "tbl")
    return ctx


# -- invalidation: a changed file is never served stale ---------------------


def _write_kv(path, rows):
    with open(path, "w") as f:
        f.write("k,v\n")
        for k, v in rows:
            f.write(f"{k},{v}\n")


def _kv_ctx(path, **settings):
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.datatypes import Field, Float64, Int64, Schema

    ctx = BallistaContext("standalone", settings=settings or None)
    ctx.register_csv("kv", str(path),
                     Schema([Field("k", Int64), Field("v", Float64)]))
    return ctx


def test_table_cache_rewrite_mid_session_misses(tmp_path):
    """File rewrite between two collects of the SAME session: the
    second scan must re-read (the signature mints a new key), and the
    stale pinned entry must not satisfy it."""
    from ballista_tpu.cache import residency

    path = tmp_path / "kv.csv"
    _write_kv(path, [(1, 10.0), (2, 20.0)])
    ctx = _kv_ctx(path)
    df = ctx.sql("SELECT SUM(v) AS s FROM kv")
    assert float(df.collect()["s"][0]) == 30.0
    cache = residency.process_table_cache()
    assert cache.stats()["fills"] >= 1

    _write_kv(path, [(1, 10.0), (2, 20.0), (3, 70.0)])
    assert float(df.collect()["s"][0]) == 100.0  # append seen

    _write_kv(path, [(1, 1.5)])
    assert float(df.collect()["s"][0]) == 1.5  # rewrite seen


def test_result_cache_file_change_mid_session_misses(tmp_path):
    """The result tier re-stats source files at lookup: a hit is only
    legal while every input file signature still matches."""
    from ballista_tpu.cache import cache_counters, reset_cache_stats

    path = tmp_path / "kv.csv"
    _write_kv(path, [(1, 2.0), (2, 3.0)])
    ctx = _kv_ctx(path, **{"result_cache.enabled": "on"})
    df = ctx.sql("SELECT SUM(v) AS s FROM kv")

    reset_cache_stats()
    first = df.collect()
    again = df.collect()
    cc = cache_counters()
    assert cc["result_cache_hits"] == 1
    assert first.equals(again)

    _write_kv(path, [(1, 2.0), (2, 3.0), (3, 5.0)])
    changed = df.collect()
    cc = cache_counters()
    assert cc["result_cache_hits"] == 1  # no stale hit
    assert float(changed["s"][0]) == 10.0


# -- byte-identity: every tier on vs off, standalone and cluster ------------

IDENTITY_QUERIES = ["q1", "q5", "q12", "q16"]


def _caches_off(monkeypatch):
    monkeypatch.setenv("BALLISTA_TABLE_CACHE", "off")
    monkeypatch.setenv("BALLISTA_DONATION", "off")
    monkeypatch.setenv("BALLISTA_RESULT_CACHE", "off")


def _caches_on(monkeypatch):
    monkeypatch.setenv("BALLISTA_TABLE_CACHE", "on")
    monkeypatch.setenv("BALLISTA_DONATION", "on")
    monkeypatch.setenv("BALLISTA_RESULT_CACHE", "on")


@pytest.mark.parametrize("qname", IDENTITY_QUERIES)
def test_identity_standalone_caches_on_vs_off(tpch_dir, monkeypatch,
                                              qname):
    from ballista_tpu.cache import residency

    _caches_off(monkeypatch)
    baseline = _standalone(tpch_dir).sql(_q(qname)).collect()

    _caches_on(monkeypatch)
    residency._reset_for_tests()
    ctx = _standalone(tpch_dir)
    df = ctx.sql(_q(qname))
    cold = df.collect()   # fills the table (and result) tiers
    warm = df.collect()   # table-cache + result-cache hit path
    pd.testing.assert_frame_equal(cold, baseline)
    pd.testing.assert_frame_equal(warm, baseline)


@pytest.mark.parametrize("caches", ["off", "on"])
def test_identity_cluster_caches_on_vs_off(tpch_dir, monkeypatch,
                                           caches, tmp_path_factory):
    """LocalCluster leg: executors fill/serve the process tiers; both
    configurations must produce the exact same frames. The off leg
    archives its frames for the on leg to diff against."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.distributed.executor import LocalCluster

    archive = tmp_path_factory.getbasetemp() / "cache_cluster_baseline"
    archive.mkdir(exist_ok=True)
    (_caches_off if caches == "off" else _caches_on)(monkeypatch)

    cluster = LocalCluster(num_executors=2, concurrent_tasks=2)
    try:
        ctx = BallistaContext.remote("localhost", cluster.port)
        register_tpch(ctx, tpch_dir, "tbl")
        for qname in IDENTITY_QUERIES:
            df = ctx.sql(_q(qname))
            got = df.collect()
            again = df.collect()  # warm pass inside the same session
            pd.testing.assert_frame_equal(again, got)
            pkl = archive / f"{qname}.pkl"
            if caches == "off":
                got.to_pickle(pkl)
            elif pkl.exists():
                pd.testing.assert_frame_equal(got, pd.read_pickle(pkl))
    finally:
        cluster.shutdown()


def test_donation_on_off_identity_and_counter(tpch_dir, monkeypatch):
    from ballista_tpu.cache import cache_counters, reset_cache_stats

    monkeypatch.setenv("BALLISTA_DONATION", "off")
    base = _standalone(tpch_dir).sql(_q("q1")).collect()

    monkeypatch.setenv("BALLISTA_DONATION", "on")
    reset_cache_stats()
    donated = _standalone(tpch_dir).sql(_q("q1")).collect()
    pd.testing.assert_frame_equal(donated, base)
    assert cache_counters()["donated_buffers"] > 0


# -- budget pressure degrades, never fails ----------------------------------


def test_governor_eviction_lru_and_dead_fill():
    """Unit-level governor contract: coldest-first eviction makes room,
    an entry that cannot fit even after evicting everything dies
    cleanly (refusal, zero residue), and accounting returns to zero."""
    from ballista_tpu.cache.residency import DeviceTableCache

    os.environ["BALLISTA_TABLE_CACHE_BUDGET_MB"] = "1"
    os.environ["BALLISTA_TABLE_CACHE_WATERMARK"] = "1.0"
    try:
        cache = DeviceTableCache()
        batch = lambda kb: np.zeros(kb << 10, dtype=np.uint8)  # noqa: E731

        fa = cache.begin_fill(("t", "a"))
        assert fa.add(batch(600)) and fa.commit()
        fb = cache.begin_fill(("t", "b"))
        assert fb.add(batch(600)) and fb.commit()  # evicts a (coldest)
        assert cache.stats()["evictions"] == 1
        assert not cache.contains(("t", "a"))
        assert cache.contains(("t", "b"))

        fc = cache.begin_fill(("t", "c"))
        assert fc.add(batch(2048)) is False  # dead: larger than budget
        assert not fc.commit()
        assert cache.stats()["refusals"] >= 1
        assert not cache.contains(("t", "c"))

        cache.invalidate()
        assert cache.governor.resident_bytes == 0
    finally:
        os.environ.pop("BALLISTA_TABLE_CACHE_BUDGET_MB", None)
        os.environ.pop("BALLISTA_TABLE_CACHE_WATERMARK", None)


def test_starved_budget_degrades_to_reingest(tpch_dir, monkeypatch):
    """Engine-level: a watermark so low every fill is refused must
    leave queries correct and unpinned — re-ingest, never an error."""
    from ballista_tpu.cache import residency

    baseline = _standalone(tpch_dir).sql(_q("q1")).collect()

    monkeypatch.setenv("BALLISTA_TABLE_CACHE_BUDGET_MB", "1")
    monkeypatch.setenv("BALLISTA_TABLE_CACHE_WATERMARK", "0.01")
    residency._reset_for_tests()
    df = _standalone(tpch_dir).sql(_q("q1"))
    starved = df.collect()
    starved2 = df.collect()
    pd.testing.assert_frame_equal(starved, baseline)
    pd.testing.assert_frame_equal(starved2, baseline)
    stats = residency.process_table_cache().stats()
    assert stats["refusals"] > 0 or stats["evictions"] > 0
    assert stats["resident_bytes"] <= int(0.01 * (1 << 20))
