"""Presorted group-by fast path (kernels/aggregate.py): the runtime
lax.cond branch that skips the O(N log N) sort when a single key is
already non-decreasing over a contiguous live prefix. Both branches must
be EXACTLY equivalent; the predicate must reject interleaved-dead and
unsorted inputs (taking the fast path there would misgroup).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ballista_tpu.kernels import aggregate as ka


def _run(keys, live, vals):
    aggs = [ka.AggInput("sum", jnp.asarray(vals), None),
            ka.AggInput("count", None, None)]
    G = 256
    r = ka.grouped_aggregate([jnp.asarray(keys)], jnp.asarray(live), aggs, G)
    ng = int(r.num_groups)
    reps = np.asarray(r.rep_indices)[:ng]
    return (ng,
            np.asarray(keys)[reps].tolist(),
            np.asarray(r.aggregates[0])[:ng].tolist(),
            np.asarray(r.aggregates[1])[:ng].tolist())


def _oracle(keys, live, vals):
    import pandas as pd

    df = pd.DataFrame({"k": keys, "v": vals})[np.asarray(live)]
    g = df.groupby("k", sort=True)["v"].agg(["sum", "count"])
    return (len(g), g.index.tolist(),
            g["sum"].tolist(), g["count"].tolist())


@pytest.mark.parametrize("case", ["sorted", "unsorted", "interleaved_dead"])
def test_fast_and_slow_paths_agree(case):
    rng = np.random.default_rng(11)
    n = 4096
    vals = rng.integers(0, 100, n).astype(np.int64)
    if case == "sorted":
        keys = np.sort(rng.integers(0, 150, n)).astype(np.int64)
        live = np.ones(n, bool)
        live[3500:] = False  # dead tail keeps the live prefix
    elif case == "unsorted":
        keys = rng.permutation(np.sort(rng.integers(0, 150, n))).astype(
            np.int64)
        live = np.ones(n, bool)
        live[3500:] = False
    else:  # dead rows interleaved: prefix test must force the slow path
        keys = np.sort(rng.integers(0, 150, n)).astype(np.int64)
        live = rng.random(n) > 0.3
    got = _run(keys, live, vals)
    exp = _oracle(keys, live, vals)
    assert got[0] == exp[0], case
    # fast path emits groups in key order (input sorted); slow path sorts —
    # compare as key->values maps to stay order-agnostic
    got_map = {k: (s, c) for k, s, c in zip(got[1], got[2], got[3])}
    exp_map = {k: (s, c) for k, s, c in zip(exp[1], exp[2], exp[3])}
    assert got_map == exp_map, case


def test_predicate_selects_fast_path_only_when_safe():
    """White-box: the branch predicate itself (prefix-live AND
    non-decreasing) — the property the fast path's correctness rests on."""
    def predicate(keys, live):
        k0 = jnp.asarray(keys)
        lv = jnp.asarray(live)
        live_prefix = jnp.all(lv[1:] <= lv[:-1])
        nondec = jnp.all(jnp.logical_or(k0[1:] >= k0[:-1],
                                        jnp.logical_not(lv[1:])))
        return bool(jnp.logical_and(live_prefix, nondec))

    assert predicate([1, 2, 2, 9], [True, True, True, False])
    assert predicate([1, 2, 2, 0], [True, True, True, False])  # dead tail
    assert not predicate([2, 1, 3, 4], [True, True, True, True])
    assert not predicate([1, 2, 3, 4], [True, False, True, True])
