"""Co-partitioned (shuffled) hash join tests.

The reference's distributed planner passes join children through unsplit
(reference: rust/scheduler/src/planner.rs:172-173), so every task holds
the whole build side. Our planner hash-shuffles BOTH join inputs on the
join keys when the estimated build side exceeds a threshold; partition p
then joins build[p] x probe[p] (the Spark-style shuffled hash join).
"""

import os

import numpy as np
import pandas as pd
import pytest

from ballista_tpu.client import BallistaContext
from ballista_tpu.distributed.planner import DistributedPlanner, find_unresolved_shuffles
from ballista_tpu.io import MemTableSource
from ballista_tpu.logical import Join, TableScan
from ballista_tpu.physical.join import JoinExec
from ballista_tpu.physical.operators import ProjectionExec, RepartitionExec
from ballista_tpu.physical.planner import PlannerOptions, create_physical_plan
from ballista_tpu import schema, Int64, serde

from benchmarks.tpch import datagen, oracle
from benchmarks.tpch.schema_def import register_tpch

QDIR = os.path.join(os.path.dirname(__file__), "..", "benchmarks", "tpch",
                    "queries")


def _mem(n, key_mod, name_prefix=""):
    s = schema((f"{name_prefix}k", Int64), (f"{name_prefix}v", Int64))
    return MemTableSource.from_pydict(
        s, {f"{name_prefix}k": np.arange(n) % key_mod,
            f"{name_prefix}v": np.arange(n)},
        num_partitions=2,
    ), s


def _find_join(plan):
    if isinstance(plan, JoinExec):
        return plan
    for c in plan.children():
        j = _find_join(c)
        if j is not None:
            return j
    return None


# ---------------------------------------------------------------------------
# planner shape
# ---------------------------------------------------------------------------


def test_planner_emits_partitioned_join_above_threshold():
    lsrc, ls = _mem(100, 10, "l")
    rsrc, rs = _mem(40, 10, "r")
    plan = Join(TableScan("l", lsrc), TableScan("r", rsrc),
                on=[("lk", "rk")], how="inner")
    opts = PlannerOptions(join_partition_threshold=10, join_partitions=4)
    phys = create_physical_plan(plan, opts)
    j = _find_join(phys)
    assert j is not None and j.partitioned
    assert all(isinstance(c, RepartitionExec) for c in j.children())
    assert all(c.num_partitions == 4 for c in j.children())
    # both sides hash the co-located join key; co-partitioned inner
    # joins build on the LARGER estimated side (l, 100 rows vs 40) so
    # output capacities ride the smaller probe side
    assert [e.name() for e in j.build.hash_exprs] == ["lk"]
    assert [e.name() for e in j.probe.hash_exprs] == ["rk"]

    # below threshold: merged-build join, unchanged
    phys2 = create_physical_plan(plan, PlannerOptions(
        join_partition_threshold=1_000_000))
    j2 = _find_join(phys2)
    assert j2 is not None and not j2.partitioned


def test_semi_join_estimate_bounds_orientation():
    """A semi/anti join estimates as its PROBE side, not the child sum:
    the membership list must not inflate a pruned input's estimate.
    q18's IN-subquery side otherwise estimated above the full lineitem
    scan and the cost swap built the wrong (cheap-to-reprobe) side."""
    lsrc, _ = _mem(100, 10, "l")
    rsrc, _ = _mem(40, 10, "r")
    ssrc, _ = _mem(500, 10, "s")  # big membership list
    pruned = Join(TableScan("r", rsrc), TableScan("s", ssrc),
                  on=[("rk", "sk")], how="semi")
    phys_semi = create_physical_plan(pruned, PlannerOptions())
    sj = _find_join(phys_semi)
    assert sj.estimated_rows() == 40  # probe side, NOT 40 + 500
    # cost swap: the truly-larger plain side (l, 100) becomes the
    # partitioned build even though r's SUBTREE sums to 540
    plan = Join(TableScan("l", lsrc), pruned,
                on=[("lk", "rk")], how="inner")
    opts = PlannerOptions(join_partition_threshold=10, join_partitions=4)
    j = _find_join(create_physical_plan(plan, opts))
    assert j is not None and j.partitioned
    assert [e.name() for e in j.build.hash_exprs] == ["lk"]
    assert [e.name() for e in j.probe.hash_exprs] == ["rk"]


def test_stage_dag_shape_for_partitioned_join():
    lsrc, _ = _mem(100, 10, "l")
    rsrc, _ = _mem(40, 10, "r")
    plan = Join(TableScan("l", lsrc), TableScan("r", rsrc),
                on=[("lk", "rk")], how="inner")
    phys = create_physical_plan(plan, PlannerOptions(
        join_partition_threshold=10, join_partitions=4))
    stages = DistributedPlanner().plan_query_stages("job1", phys)
    # two shuffle-producing stages (one per join side) + the final stage
    shuffle_stages = [s for s in stages if s.shuffle_hash_exprs]
    assert len(shuffle_stages) == 2
    assert all(s.shuffle_output_partitions == 4 for s in shuffle_stages)
    final = stages[-1]
    unresolved = find_unresolved_shuffles(final.child)
    assert sorted(sid for u in unresolved for sid in u.query_stage_ids) == \
        sorted(s.stage_id for s in shuffle_stages)
    # the final stage's join keeps the partitioned flag through serde
    rt = serde.physical_from_proto(serde.physical_to_proto(final.child))
    j = _find_join(rt)
    assert j is not None and j.partitioned


# ---------------------------------------------------------------------------
# correctness: TPC-H q5/q9/q18 with every join forced partitioned
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tpch_part(tmp_path_factory):
    data_dir = str(tmp_path_factory.mktemp("tpch_pjoin"))
    datagen.generate(data_dir, scale=0.002, num_parts=2)
    ctx = BallistaContext.standalone(**{
        "join.partitioned.threshold": "1",  # force EVERY eligible join
        "join.partitions": "4",
    })
    register_tpch(ctx, data_dir, "tbl")
    return ctx, oracle.load_tables(data_dir)


@pytest.mark.parametrize("qname", ["q5", "q9", "q18"])
def test_tpch_partitioned_join(tpch_part, qname):
    ctx, tables = tpch_part
    sql = open(os.path.join(QDIR, f"{qname}.sql")).read()
    got = ctx.sql(sql).collect().reset_index(drop=True)
    exp = oracle.ORACLES[qname](tables).reset_index(drop=True)
    assert len(got) == len(exp)
    for c in exp.columns:
        g, e = got[c], exp[c]
        if e.dtype.kind in "fc":
            np.testing.assert_allclose(g.astype(float), e.astype(float),
                                       rtol=1e-6, atol=1e-6, err_msg=c)
        else:
            np.testing.assert_array_equal(g.to_numpy(), e.to_numpy(),
                                          err_msg=c)


def test_repartition_compaction_with_non_pow2_capacity():
    """round_capacity(n) can exceed a caller-chosen non-power-of-two batch
    capacity; the compacting RepartitionExec must clamp, not emit a batch
    whose selection is longer than its columns."""
    from ballista_tpu.physical.operators import RepartitionExec, ScanExec
    from ballista_tpu import expr as ex2

    s = schema(("k", Int64), ("v", Int64))
    src = MemTableSource.from_pydict(
        s, {"k": np.zeros(10, np.int64), "v": np.arange(10)},
        num_partitions=1, capacity=10,
    )
    rp = RepartitionExec(ScanExec("t", src), 2, [ex2.col("k")])
    got = []
    for p in range(2):
        for b in rp.execute(p):
            assert b.capacity == int(b.columns[0].values.shape[0])
            d = b.to_pydict()
            got.extend(d["v"].tolist())
    assert sorted(got) == list(range(10))


# ---------------------------------------------------------------------------
# through the distributed cluster
# ---------------------------------------------------------------------------


def test_cluster_partitioned_join(tmp_path):
    from ballista_tpu.distributed.executor import LocalCluster
    from ballista_tpu.io import TblSource
    from ballista_tpu import Utf8, Decimal

    d = tmp_path / "dim.tbl"
    d.write_text("".join(f"{i}|cat{i % 2}|\n" for i in range(7)))
    f = tmp_path / "fact.tbl"
    f.write_text("".join(f"{i}|{i % 7}|{i + 0.5:.2f}|\n" for i in range(60)))

    dim_s = schema(("dkey", Int64), ("cat", Utf8))
    fact_s = schema(("fid", Int64), ("fkey", Int64), ("v", Decimal(2)))
    cluster = LocalCluster(num_executors=2, concurrent_tasks=2)
    try:
        ctx = BallistaContext.remote(
            "localhost", cluster.port,
            **{"join.partitioned.threshold": "1", "join.partitions": "3"},
        )
        ctx.register_source("dim", TblSource(str(d), dim_s),
                            primary_key="dkey")
        ctx.register_source("fact", TblSource(str(f), fact_s))
        got = ctx.sql(
            "select cat, sum(v) as sv, count(*) as n from fact, dim "
            "where fkey = dkey group by cat order by cat"
        ).collect()

        a = np.arange(60)
        fd = pd.DataFrame({"fkey": a % 7, "v": a + 0.5})
        fd["cat"] = fd.fkey.map(lambda k: f"cat{k % 2}")
        exp = fd.groupby("cat").agg(sv=("v", "sum"), n=("v", "size")) \
            .reset_index().sort_values("cat")
        np.testing.assert_array_equal(got["cat"], exp["cat"])
        np.testing.assert_allclose(got["sv"], exp["sv"], rtol=1e-9)
        np.testing.assert_array_equal(got["n"], exp["n"])
    finally:
        cluster.shutdown()
