"""ICI mesh shuffle integrated into the distributed runtime.

A hash-shuffle SQL aggregation scheduled onto a mesh-owning executor must
run as ONE fused SPMD program (lax.all_to_all row exchange + per-device
final aggregation) with NO shuffle files written through the data plane —
the BASELINE config-4 rehearsal ("q5 shuffle -> ICI all_to_all"). The
host-file shuffle (reference model: shuffle_reader.rs:77-99) remains the
cross-host path.
"""

import os

import numpy as np
import pandas as pd
import pytest

from ballista_tpu import schema, Int64, Utf8, serde
from ballista_tpu.client import BallistaContext
from ballista_tpu.distributed.executor import LocalCluster
from ballista_tpu.distributed.scheduler import _fuse_mesh_stages
from ballista_tpu.distributed.planner import DistributedPlanner
from ballista_tpu.io import MemTableSource
from ballista_tpu.logical import LogicalPlanBuilder
from ballista_tpu.physical.mesh_agg import MeshAggExec
from ballista_tpu.physical.planner import PlannerOptions, create_physical_plan
from ballista_tpu import col, sum_, count


def _plan_shuffled_agg(src):
    plan = (
        LogicalPlanBuilder.scan("t", src)
        .aggregate([col("k")], [sum_(col("v")).alias("sv"),
                                count().alias("n")])
        .build()
    )
    phys = create_physical_plan(plan, PlannerOptions(agg_partitions=4))
    return DistributedPlanner().plan_query_stages("j1", phys)


def _mem(tmp_path, n=500, mod=23, parts=3, name="t"):
    from ballista_tpu.io import TblSource

    s = schema(("k", Utf8), ("v", Int64))
    rng = np.random.default_rng(7)
    keys = [f"g{i}" for i in rng.integers(0, mod, n)]
    vals = rng.integers(0, 100, n)
    d = tmp_path / name
    d.mkdir()
    per = -(-n // parts)
    for p in range(parts):
        lines = [f"{keys[i]}|{vals[i]}|"
                 for i in range(p * per, min((p + 1) * per, n))]
        (d / f"part{p}.tbl").write_text("\n".join(lines) + "\n")
    return TblSource(str(d), s), pd.DataFrame({"k": keys, "v": vals})


def test_fusion_pattern_and_serde(eight_devices, tmp_path):
    src, _ = _mem(tmp_path)
    stages = _plan_shuffled_agg(src)
    # unfused: a hash-shuffle producer stage + a final-agg consumer
    assert any(s.shuffle_hash_exprs for s in stages)

    fused = _fuse_mesh_stages(stages, 8)
    assert len(fused) == len(stages) - 1
    mesh_stage = fused[-1]
    assert isinstance(mesh_stage.child, MeshAggExec)
    assert mesh_stage.child.n_devices == 8
    # the fused node round-trips through proto serde
    rt = serde.physical_from_proto(serde.physical_to_proto(mesh_stage.child))
    assert isinstance(rt, MeshAggExec) and rt.n_devices == 8
    assert [e.name() for e in rt.hash_exprs] == ["k"]

    # gate respected: no mesh -> untouched
    assert _fuse_mesh_stages(stages, 0) == stages


def test_mesh_task_assignment_respects_num_devices():
    """A mesh-fused task must not be handed to an executor with fewer
    devices; plain tasks still flow to it."""
    from ballista_tpu.distributed.state import MemoryBackend, SchedulerState
    from ballista_tpu.distributed.types import PartitionId

    state = SchedulerState(MemoryBackend())
    state.save_stage_plan("j1", 1, b"", 1, [], mesh_devices=8)
    state.save_stage_plan("j1", 2, b"", 1, [], mesh_devices=0)
    state._ready = [PartitionId("j1", 1, 0), PartitionId("j1", 2, 0)]
    # 1-device executor: skips the mesh task, gets the plain one
    assert state.next_task(num_devices=1) == PartitionId("j1", 2, 0)
    # 8-device executor: gets the mesh task
    assert state.next_task(num_devices=8) == PartitionId("j1", 1, 0)
    assert state.next_task(num_devices=8) is None


def test_cluster_mesh_shuffle_agg(eight_devices, tmp_path):
    src, df = _mem(tmp_path, n=800, mod=31)
    cluster = LocalCluster(num_executors=1, concurrent_tasks=2,
                          num_devices=8)
    try:
        ctx = BallistaContext.remote(
            "localhost", cluster.port,
            **{"agg.partitions": "8", "mesh.devices": "8"},
        )
        ctx.register_source("t", src)
        got = ctx.sql(
            "select k, sum(v) as sv, count(*) as n from t group by k order by k"
        ).collect()

        exp = df.groupby("k").agg(sv=("v", "sum"), n=("v", "size")) \
            .reset_index().sort_values("k")
        np.testing.assert_array_equal(got["k"], exp["k"])
        np.testing.assert_array_equal(got["sv"].astype(np.int64),
                                      exp["sv"].astype(np.int64))
        np.testing.assert_array_equal(got["n"].astype(np.int64),
                                      exp["n"].astype(np.int64))

        # the mesh path must leave NO shuffle files behind: the exchange
        # rode lax.all_to_all inside one SPMD program
        shuffle_files = []
        for e in cluster.executors:
            for root, _, files in os.walk(e.config.work_dir):
                shuffle_files += [f for f in files
                                  if f.startswith("shuffle-")]
        assert shuffle_files == [], f"host shuffle files written: {shuffle_files}"
    finally:
        cluster.shutdown()


def test_cluster_mesh_shuffle_join(eight_devices, tmp_path):
    """A partitioned inner join under mesh.devices fuses into ONE
    MeshJoinExec task: both sides exchanged over lax.all_to_all, joined
    per device, ZERO shuffle files — BASELINE config 4's q5 shape."""
    from ballista_tpu import Decimal

    d = tmp_path / "dim"
    d.mkdir()
    (d / "p0.tbl").write_text("".join(f"{i}|cat{i % 3}|\n" for i in range(11)))
    f = tmp_path / "fact"
    f.mkdir()
    for part in range(2):
        rows = [f"{i}|{i % 11}|{i + 0.5:.2f}|\n"
                for i in range(120) if i % 2 == part]
        (f / f"p{part}.tbl").write_text("".join(rows))
    from ballista_tpu.io import TblSource

    dim_s = schema(("dkey", Int64), ("cat", Utf8))
    fact_s = schema(("fid", Int64), ("fkey", Int64), ("v", Decimal(2)))
    cluster = LocalCluster(num_executors=1, concurrent_tasks=2,
                          num_devices=8)
    try:
        ctx = BallistaContext.remote(
            "localhost", cluster.port,
            **{"join.partitioned.threshold": "1", "join.partitions": "8",
               "mesh.devices": "8"},
        )
        ctx.register_source("dim", TblSource(str(d), dim_s),
                            primary_key="dkey")
        ctx.register_source("fact", TblSource(str(f), fact_s))
        got = ctx.sql(
            "select cat, sum(v) as sv, count(*) as n from fact, dim "
            "where fkey = dkey group by cat order by cat"
        ).collect()

        a = np.arange(120)
        fd = pd.DataFrame({"fkey": a % 11, "v": a + 0.5})
        fd["cat"] = fd.fkey.map(lambda k: f"cat{k % 3}")
        exp = fd.groupby("cat").agg(sv=("v", "sum"), n=("v", "size")) \
            .reset_index().sort_values("cat")
        np.testing.assert_array_equal(got["cat"], exp["cat"])
        np.testing.assert_allclose(got["sv"], exp["sv"], rtol=1e-9)
        np.testing.assert_array_equal(got["n"].astype(np.int64),
                                      exp["n"].astype(np.int64))

        shuffle_files = []
        for e in cluster.executors:
            for root, _, files in os.walk(e.config.work_dir):
                shuffle_files += [x for x in files
                                  if x.startswith("shuffle-")]
        assert shuffle_files == [], f"host shuffle files written: {shuffle_files}"
    finally:
        cluster.shutdown()


def test_cluster_file_shuffle_without_mesh_setting(eight_devices, tmp_path):
    """Same query WITHOUT mesh.devices: the host-file shuffle runs (and
    still matches), proving the fusion is what removed the files above."""
    src, df = _mem(tmp_path, n=300, mod=11)
    cluster = LocalCluster(num_executors=1, concurrent_tasks=2)
    try:
        ctx = BallistaContext.remote("localhost", cluster.port,
                                     **{"agg.partitions": "4"})
        ctx.register_source("t", src)
        got = ctx.sql(
            "select k, sum(v) as sv from t group by k order by k"
        ).collect()
        exp = df.groupby("k").agg(sv=("v", "sum")).reset_index() \
            .sort_values("k")
        np.testing.assert_array_equal(got["k"], exp["k"])
        np.testing.assert_array_equal(got["sv"].astype(np.int64),
                                      exp["sv"].astype(np.int64))
        shuffle_files = []
        for e in cluster.executors:
            for root, _, files in os.walk(e.config.work_dir):
                shuffle_files += [f for f in files
                                  if f.startswith("shuffle-")]
        assert shuffle_files, "expected host shuffle files on the file path"
    finally:
        cluster.shutdown()


def _wait_registered(cluster, n=1, t=5.0):
    import time

    deadline = time.time() + t
    while len(cluster.state.get_executors_metadata()) < n:
        assert time.time() < deadline, "executors never registered"
        time.sleep(0.05)


def test_mesh_fusion_driven_by_fleet_reports(eight_devices, tmp_path):
    """Fusion fires with NO client mesh.devices setting: the scheduler
    reads the fleet's uniformly-reported num_devices (PollWork metadata)
    — cluster truth, not a client hint."""
    src, df = _mem(tmp_path, n=600, mod=19)
    cluster = LocalCluster(num_executors=1, concurrent_tasks=2,
                          num_devices=8)
    try:
        _wait_registered(cluster)
        ctx = BallistaContext.remote("localhost", cluster.port,
                                     **{"agg.partitions": "8"})
        ctx.register_source("t", src)
        got = ctx.sql(
            "select k, sum(v) as sv from t group by k order by k"
        ).collect()
        exp = df.groupby("k").agg(sv=("v", "sum")).reset_index() \
            .sort_values("k")
        np.testing.assert_array_equal(got["k"], exp["k"])
        np.testing.assert_array_equal(got["sv"].astype(np.int64),
                                      exp["sv"].astype(np.int64))
        # fused => the exchange rode all_to_all: zero shuffle files
        shuffle_files = []
        for e in cluster.executors:
            for root, _, files in os.walk(e.config.work_dir):
                shuffle_files += [f for f in files
                                  if f.startswith("shuffle-")]
        assert shuffle_files == [], \
            f"fleet-driven fusion did not fire: {shuffle_files}"
    finally:
        cluster.shutdown()


def test_lying_client_cannot_change_plan_shape(eight_devices, tmp_path):
    """A client claiming mesh.devices=8 against a 1-device fleet must
    fail the job loudly — never silently fuse OR silently unfuse."""
    from ballista_tpu.errors import ClusterError

    src, _ = _mem(tmp_path, n=100, mod=5)
    cluster = LocalCluster(num_executors=1, concurrent_tasks=2,
                          num_devices=1)
    try:
        _wait_registered(cluster)
        ctx = BallistaContext.remote(
            "localhost", cluster.port,
            **{"agg.partitions": "4", "mesh.devices": "8"},
        )
        ctx.register_source("t", src)
        with pytest.raises(ClusterError, match="mesh.devices=8"):
            ctx.sql("select k, sum(v) as sv from t group by k").collect()
    finally:
        cluster.shutdown()
