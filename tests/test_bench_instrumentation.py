"""Gate test for bench.py's per-stage instrumentation.

Round-4 regression: the scanner's return arity changed (validity masks
added) and ``bench.instrument_q1`` silently broke — ``BENCH_r04.json``
recorded ``stages_error`` instead of the parse/h2d/kernel decomposition.
Nothing in the gate exercised the instrumentation, so this test runs it
end-to-end on tiny data (SF0.002, 2 partitions so the multi-partition
concat path is covered too) and asserts the stage fields are populated.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture(scope="module")
def tiny_data(tmp_path_factory):
    from benchmarks.tpch import datagen

    data_dir = str(tmp_path_factory.mktemp("bench_instr"))
    datagen.generate(data_dir, scale=0.002, num_parts=2)
    return data_dir


def test_instrument_q1_populates_stages(tiny_data):
    import bench

    out = bench.instrument_q1(tiny_data, runs=1)
    # parse / h2d / kernel triplet must all be present and positive
    for key in ("parse_s", "parse_mb_per_s", "h2d_s", "rows",
                "kernel_s", "kernel_rows_per_s", "kernel_aot_compile_s"):
        assert key in out, f"missing stage field {key}: {out}"
    assert out["rows"] > 0
    assert out["kernel_s"] > 0
    assert out["kernel_rows_per_s"] > 0


def test_cold_phase_split_fields(tiny_data, monkeypatch):
    """bench.cold_phase_split (the source of the parse_seconds /
    h2d_seconds / execute_seconds JSON fields) must populate all phase
    fields, and — with the ingest pipeline gated off, where phase time
    is consumer-thread time — they must sum to the wall time."""
    from ballista_tpu import ingest

    monkeypatch.setenv("BALLISTA_INGEST_THREADS", "1")
    monkeypatch.setenv("BALLISTA_PREFETCH_BATCHES", "0")
    ingest.reconfigure()
    try:
        import bench
        from ballista_tpu.client import BallistaContext
        from benchmarks.tpch.schema_def import TPCH_PKS, TPCH_SCHEMAS

        ctx = BallistaContext.standalone()
        ctx.register_tbl("lineitem", os.path.join(tiny_data, "lineitem"),
                         TPCH_SCHEMAS["lineitem"],
                         primary_key=TPCH_PKS["lineitem"])
        sql = open(os.path.join(os.path.dirname(__file__), "..",
                                "benchmarks", "tpch", "queries",
                                "q1.sql")).read()
        _, phases = bench.cold_phase_split(
            lambda: ctx.sql(sql).collect())
    finally:
        monkeypatch.undo()
        ingest.reconfigure()
    for key in ("wall_seconds", "parse_seconds", "h2d_seconds",
                "execute_seconds"):
        assert key in phases, f"missing {key}: {phases}"
        assert phases[key] >= 0
    assert phases["parse_seconds"] > 0
    assert phases["h2d_seconds"] > 0
    total = (phases["parse_seconds"] + phases["h2d_seconds"]
             + phases["execute_seconds"])
    wall = phases["wall_seconds"]
    # serial mode: parse + h2d + execute ≈ wall (rounding noise only)
    assert abs(total - wall) <= max(0.05 * wall, 0.02), phases
