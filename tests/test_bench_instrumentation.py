"""Gate test for bench.py's per-stage instrumentation.

Round-4 regression: the scanner's return arity changed (validity masks
added) and ``bench.instrument_q1`` silently broke — ``BENCH_r04.json``
recorded ``stages_error`` instead of the parse/h2d/kernel decomposition.
Nothing in the gate exercised the instrumentation, so this test runs it
end-to-end on tiny data (SF0.002, 2 partitions so the multi-partition
concat path is covered too) and asserts the stage fields are populated.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture(scope="module")
def tiny_data(tmp_path_factory):
    from benchmarks.tpch import datagen

    data_dir = str(tmp_path_factory.mktemp("bench_instr"))
    datagen.generate(data_dir, scale=0.002, num_parts=2)
    return data_dir


def test_instrument_q1_populates_stages(tiny_data):
    import bench

    out = bench.instrument_q1(tiny_data, runs=1)
    # parse / h2d / kernel triplet must all be present and positive
    for key in ("parse_s", "parse_mb_per_s", "h2d_s", "rows",
                "kernel_s", "kernel_rows_per_s", "kernel_aot_compile_s"):
        assert key in out, f"missing stage field {key}: {out}"
    assert out["rows"] > 0
    assert out["kernel_s"] > 0
    assert out["kernel_rows_per_s"] > 0
