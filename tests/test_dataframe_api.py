"""DataFrame verb surface (reference: BallistaDataFrame,
rust/client/src/context.rs:241-314 — select_columns/select/filter/
aggregate/limit/sort/repartition/explain/schema; its join is a TODO at
:287-290, ours works). One chained scenario per verb family, checked
against pandas."""

import numpy as np
import pandas as pd

from ballista_tpu import col, count, lit, schema, sum_, Int64, Utf8
from ballista_tpu.client import BallistaContext
from ballista_tpu.io import MemTableSource


def _ctx():
    ctx = BallistaContext.standalone()
    n = 200
    rng = np.random.default_rng(2)
    data = {"k": rng.integers(0, 9, n), "v": rng.integers(0, 50, n),
            "tag": [f"t{i % 4}" for i in range(n)]}
    ctx.register_source("t", MemTableSource.from_pydict(
        schema(("k", Int64), ("v", Int64), ("tag", Utf8)), data,
        num_partitions=2))
    dims = {"dk": np.arange(9), "w": np.arange(9) * 10}
    ctx.register_source("d", MemTableSource.from_pydict(
        schema(("dk", Int64), ("w", Int64)), dims), primary_key="dk")
    return ctx, pd.DataFrame(data), pd.DataFrame(dims)


def test_dataframe_verb_chain():
    ctx, t, d = _ctx()
    df = (
        ctx.table("t")
        .filter(col("v") > lit(10))
        .join(ctx.table("d"), on=[("k", "dk")])
        .select(col("k"), col("v"), col("w"), col("tag"))
        .aggregate([col("k")], [sum_(col("v") + col("w")).alias("s"),
                                count().alias("n")])
        .sort(col("k").asc())
        .limit(5)
    )
    assert list(df.schema().names()) == ["k", "s", "n"]
    assert "Aggregate" in df.explain()
    got = df.collect()

    exp = (
        t[t.v > 10].merge(d, left_on="k", right_on="dk")
        .assign(sv=lambda x: x.v + x.w)
        .groupby("k").agg(s=("sv", "sum"), n=("sv", "size"))
        .reset_index().sort_values("k").head(5)
    )
    np.testing.assert_array_equal(got["k"], exp["k"])
    np.testing.assert_array_equal(got["s"].astype(np.int64),
                                  exp["s"].astype(np.int64))
    np.testing.assert_array_equal(got["n"].astype(np.int64),
                                  exp["n"].astype(np.int64))


def test_dataframe_select_columns_repartition_count():
    ctx, t, _ = _ctx()
    df = ctx.table("t").select_columns("k", "v").repartition(4, [col("k")])
    assert df.count() == len(t)
    got = df.collect()
    assert sorted(got.columns) == ["k", "v"]
    assert int(got["v"].sum()) == int(t.v.sum())


def test_register_table_view_semantics():
    """A registered DataFrame acts as a named view in SQL — the role the
    reference's DFTableAdapter plays (rust/core/src/datasource.rs:28-66):
    referencing SQL inlines the frame's logical plan, including joins
    against base tables."""
    ctx, tdf, ddf = _ctx()
    view = ctx.sql("select k, sum(v) as sv from t group by k")
    ctx.register_table("agg_view", view)

    got = ctx.sql(
        "select a.k, a.sv, d.w from agg_view a, d where a.k = d.dk "
        "order by a.k"
    ).collect()
    exp = (tdf.groupby("k").agg(sv=("v", "sum")).reset_index()
           .merge(ddf, left_on="k", right_on="dk")
           .sort_values("k")[["k", "sv", "w"]])
    np.testing.assert_array_equal(got["k"], exp["k"])
    np.testing.assert_array_equal(got["sv"].astype(np.int64),
                                  exp["sv"].astype(np.int64))
    np.testing.assert_array_equal(got["w"].astype(np.int64),
                                  exp["w"].astype(np.int64))

    # views compose: a view over a view
    ctx.register_table("top", ctx.sql(
        "select k, sv from agg_view where sv > 100"))
    got2 = ctx.sql("select count(*) as n from top").collect()
    exp2 = int((tdf.groupby("k")["v"].sum() > 100).sum())
    assert int(got2["n"][0]) == exp2


def test_view_plan_isolated_from_mutation():
    """register_table snapshots the plan: executing the original frame
    (which resolves scalar subqueries in place, baking literals into its
    expr nodes) must not contaminate the view, and repeated view queries
    must be self-consistent. Views pin their sources at registration —
    the same inlined-plan semantics as the reference's DFTableAdapter
    (reference: rust/core/src/datasource.rs:28-66)."""
    from ballista_tpu import schema, Int64
    from ballista_tpu.client import BallistaContext

    ctx = BallistaContext.standalone()
    ctx.register_memtable("base", schema(("v", Int64)), {"v": [1, 2, 3]})
    df = ctx.sql("select v from base where v > (select min(v) from base)")
    ctx.register_table("big", df)
    assert sorted(df.collect()["v"]) == [2, 3]  # mutates df's own plan
    out1 = ctx.sql("select v from big order by v").collect()
    assert list(out1["v"]) == [2, 3]
    # re-registering the base name does NOT rebind the view (pinned
    # source), and must not break or contaminate it either
    ctx.register_memtable("base", schema(("v", Int64)), {"v": [10, 20, 30]})
    out2 = ctx.sql("select v from big order by v").collect()
    assert list(out2["v"]) == [2, 3]
    # ...while new queries against the re-registered base see new data
    out3 = ctx.sql("select min(v) as m from base").collect()
    assert out3["m"][0] == 10


def test_view_guard_only_fires_on_table_position():
    from ballista_tpu.distributed.client import _sql_references_table

    assert _sql_references_table("select * from total", "total")
    assert _sql_references_table("select * from t join total on a=b", "total")
    assert _sql_references_table("select * from t, total", "total")
    assert _sql_references_table("SELECT * FROM TOTAL", "total")
    # alias / string literal / unrelated ident must not fire
    assert not _sql_references_table("select sum(v) as total from t", "total")
    assert not _sql_references_table("select 'total' from t", "total")
    assert not _sql_references_table("select f(a, total) from t", "total")
    assert not _sql_references_table("select * from totals", "total")
