"""Multi-tenant serving benchmark: K concurrent sessions against ONE
warm LocalCluster (ROADMAP item 4c).

Each session is its own ``BallistaContext`` (own ``session.id``, so the
admission plane and ``system.sessions`` metering see real tenants)
running a mixed TPC-H workload (q1/q3/q5/q12/q16/q18, rotated per
session so the plan-shape interleaving differs across tenants) through
the admission gate. Prints ONE JSON line:

    {"metric": "serving_qps", "value": <queries/s>,
     "serving_p50_seconds": ..., "serving_p99_seconds": ...,
     "serving_sheds": ..., "serving_errors": ..., ...}

``dev/check_bench_regress.py`` gates serving_qps (higher), the latency
percentiles (lower) and serving_errors (must stay 0) between rounds.

Usage:
    python bench_serving.py [--scale 0.05] [--data DIR] [--sessions 4]
                            [--queries-per-session 6] [--executors 2]
                            [--slots 2] [--max-running 4]
                            [--session-quota 2]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

QUERY_MIX = ("q1", "q3", "q5", "q12", "q16", "q18")


def _percentile(sorted_vals, p: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(round(p * (len(sorted_vals) - 1))),
              len(sorted_vals) - 1)
    return sorted_vals[idx]


def run_serving(data_dir: str, sessions: int = 4,
                queries_per_session: int = 6, executors: int = 2,
                slots: int = 2, max_running: int = 4,
                session_quota: int = 2, job_timeout: float = 600.0,
                mix=QUERY_MIX) -> dict:
    """The measured phase: warm the cluster (one pass over the mix on a
    warmup session — jit compiles amortize exactly like a long-lived
    serving deployment), then storm it with K concurrent sessions and
    report latency percentiles, throughput and admission decisions."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.distributed.executor import LocalCluster
    from ballista_tpu.errors import AdmissionRejected
    from benchmarks.tpch.schema_def import register_tpch

    qdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "benchmarks", "tpch", "queries")
    sqls = {q: open(os.path.join(qdir, f"{q}.sql")).read() for q in mix}

    cluster = LocalCluster(num_executors=executors,
                           concurrent_tasks=slots)
    try:
        # -- warm pass: one unloaded run of every mix query ----------------
        warm_ctx = BallistaContext.remote(
            "localhost", cluster.port,
            **{"job.timeout": str(job_timeout),
               "session.id": "serving-warmup"})
        register_tpch(warm_ctx, data_dir, "tbl")
        solo = {}
        for q in mix:
            t0 = time.time()
            warm_ctx.sql(sqls[q]).collect()
            solo[q] = round(time.time() - t0, 4)

        # -- the storm -----------------------------------------------------
        svc = cluster.service
        admitted0 = svc.admission.admitted_total
        sheds0 = svc.admission.sheds_total
        latencies: list = []
        errors: list = []
        lat_lock = threading.Lock()
        peak_queue = [0]
        stop = threading.Event()

        def watch_queue():
            while not stop.is_set():
                peak_queue[0] = max(peak_queue[0],
                                    svc.admission.queue_depth())
                time.sleep(0.05)

        watcher = threading.Thread(target=watch_queue, daemon=True)
        watcher.start()

        def run_session(idx: int):
            settings = {
                "job.timeout": str(job_timeout),
                "session.id": f"serving-{idx}",
                "admission.max_running_jobs": str(max_running),
                "admission.max_session_jobs": str(session_quota),
            }
            ctx = BallistaContext.remote("localhost", cluster.port,
                                         **settings)
            register_tpch(ctx, data_dir, "tbl")
            for j in range(queries_per_session):
                q = mix[(idx + j) % len(mix)]
                t0 = time.time()
                try:
                    ctx.sql(sqls[q]).collect()
                except AdmissionRejected as e:
                    # terminal shed (client retries exhausted): counted
                    # separately — not an engine error
                    with lat_lock:
                        errors.append((q, f"shed:{e.reason}"))
                except Exception as e:  # noqa: BLE001 - recorded
                    with lat_lock:
                        errors.append((q, f"{type(e).__name__}: {e}"))
                else:
                    with lat_lock:
                        latencies.append((q, time.time() - t0))

        threads = [threading.Thread(target=run_session, args=(i,))
                   for i in range(sessions)]
        t0 = time.time()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall = time.time() - t0
        stop.set()
        watcher.join(1)

        lats = sorted(s for _, s in latencies)
        per_query = {}
        for q, s in latencies:
            per_query.setdefault(q, []).append(s)
        result = {
            "metric": "serving_qps",
            "unit": "queries/s",
            "value": round(len(lats) / wall, 3) if wall > 0 else 0.0,
            "serving_wall_seconds": round(wall, 3),
            "serving_sessions": sessions,
            "serving_queries": sessions * queries_per_session,
            "serving_completed": len(lats),
            "serving_errors": len([e for e in errors
                                   if not e[1].startswith("shed:")]),
            "serving_sheds": (svc.admission.sheds_total - sheds0),
            "serving_admitted": (svc.admission.admitted_total
                                 - admitted0),
            "serving_peak_queue_depth": peak_queue[0],
            "serving_p50_seconds": round(_percentile(lats, 0.50), 4),
            "serving_p99_seconds": round(_percentile(lats, 0.99), 4),
            "serving_max_seconds": round(lats[-1], 4) if lats else 0.0,
            "serving_solo_seconds": solo,
            "serving_query_p50": {
                q: round(_percentile(sorted(v), 0.5), 4)
                for q, v in sorted(per_query.items())},
        }
        if errors:
            result["serving_error_sample"] = str(errors[:3])[:300]
        return result
    finally:
        cluster.shutdown()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.05)
    ap.add_argument("--data", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks",
        "data_serving"))
    ap.add_argument("--sessions", type=int, default=4)
    ap.add_argument("--queries-per-session", type=int, default=6)
    ap.add_argument("--executors", type=int, default=2)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--max-running", type=int, default=4)
    ap.add_argument("--session-quota", type=int, default=2)
    args = ap.parse_args()

    from benchmarks.tpch import datagen

    data_dir = os.path.join(args.data, f"sf{args.scale}")
    marker = os.path.join(data_dir, ".complete")
    if not os.path.exists(marker):
        print(f"# generating TPC-H SF{args.scale} into {data_dir}",
              file=sys.stderr)
        datagen.generate(data_dir, scale=args.scale, num_parts=2)
        open(marker, "w").write("ok\n")

    result = run_serving(
        data_dir, sessions=args.sessions,
        queries_per_session=args.queries_per_session,
        executors=args.executors, slots=args.slots,
        max_running=args.max_running,
        session_quota=args.session_quota)
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
