"""Multi-tenant serving benchmark: K concurrent sessions against ONE
warm LocalCluster (ROADMAP item 4c), plus the durable-control-plane
phases (restart recovery, demand-driven autoscaling).

Each session is its own ``BallistaContext`` (own ``session.id``, so the
admission plane and ``system.sessions`` metering see real tenants)
running a mixed TPC-H workload (q1/q3/q5/q12/q16/q18, rotated per
session so the plan-shape interleaving differs across tenants) through
the admission gate. Prints ONE JSON line:

    {"metric": "serving_qps", "value": <queries/s>,
     "serving_p50_seconds": ..., "serving_p99_seconds": ...,
     "serving_sheds": ..., "serving_errors": ..., ...}

The serving line also carries the always-on latency ledger's per-lane
view (docs/observability.md): ``serving_<phase>_p50_seconds`` /
``serving_<phase>_p99_seconds`` for every ledger phase, the number of
storm ledgers observed (``serving_ledgers``) and ``p99_attribution`` —
the lane(s) where the p99 exemplar query diverges most from the p50
centroid, i.e. the place to look first when the tail regresses.

``--phase restart`` measures scheduler restart recovery over a durable
sqlite backend: submit a mixed batch (one admitted + planned, the rest
queued), abandon the service mid-flight, rebuild it on the same file
and time ``recover()`` — the line carries ``recovery_seconds`` and
``recovered_jobs``. ``--phase autoscale`` storms a min-sized cluster
with a 2x session burst under the autoscaler and reports
``autoscale_events`` and the burst's tail latency
(``autoscale_p99_seconds``).

``dev/check_bench_regress.py`` gates serving_qps (higher), the latency
percentiles and recovery_seconds (lower), recovered_jobs /
autoscale_events (nonzero) and the error counts (zero) between rounds.

Usage:
    python bench_serving.py [--phase serving|restart|autoscale]
                            [--scale 0.05] [--data DIR] [--sessions 4]
                            [--queries-per-session 6] [--executors 2]
                            [--slots 2] [--max-running 4]
                            [--session-quota 2]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

QUERY_MIX = ("q1", "q3", "q5", "q12", "q16", "q18")


def _percentile(sorted_vals, p: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(round(p * (len(sorted_vals) - 1))),
              len(sorted_vals) - 1)
    return sorted_vals[idx]


def _ledger_phase_vals(ledgers, phase: str):
    """Sorted per-query seconds of one ledger phase (the synthetic
    ``unattributed`` phase reads the remainder field)."""
    if phase == "unattributed":
        vals = [float(e.get("unattributed_seconds", 0.0))
                for e in ledgers]
    else:
        vals = [float((e.get("phases") or {}).get(phase, 0.0))
                for e in ledgers]
    return sorted(vals)


def _p99_attribution(ledgers) -> str:
    """Name the lane(s) where the p99 exemplar query diverges most from
    the per-lane p50 centroid — "where did the tail go". Lanes within
    25% of the worst divergence all make the cut (joined with ``+``);
    a perfectly flat tail falls back to the exemplar's largest lane,
    so the attribution is non-empty whenever any ledger exists."""
    if not ledgers:
        return ""
    from ballista_tpu.observability.ledger import LEDGER_PHASES

    by_wall = sorted(ledgers,
                     key=lambda e: float(e.get("wall_seconds", 0.0)))
    exemplar = by_wall[min(int(round(0.99 * (len(by_wall) - 1))),
                           len(by_wall) - 1)]
    ex_phases = dict(exemplar.get("phases") or {})
    ex_phases["unattributed"] = float(
        exemplar.get("unattributed_seconds", 0.0))
    divergence = {}
    for phase in (*LEDGER_PHASES, "unattributed"):
        p50 = _percentile(_ledger_phase_vals(ledgers, phase), 0.50)
        divergence[phase] = float(ex_phases.get(phase, 0.0)) - p50
    top = max(divergence.values())
    if top <= 0:
        return max(ex_phases, key=lambda p: ex_phases.get(p, 0.0))
    return "+".join(p for p, d in sorted(divergence.items(),
                                         key=lambda kv: -kv[1])
                    if d >= 0.25 * top)


def run_serving(data_dir: str, sessions: int = 4,
                queries_per_session: int = 6, executors: int = 2,
                slots: int = 2, max_running: int = 4,
                session_quota: int = 2, job_timeout: float = 600.0,
                mix=QUERY_MIX) -> dict:
    """The measured phase: warm the cluster (one pass over the mix on a
    warmup session — jit compiles amortize exactly like a long-lived
    serving deployment), then storm it with K concurrent sessions and
    report latency percentiles, throughput and admission decisions."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.distributed.executor import LocalCluster
    from ballista_tpu.errors import AdmissionRejected
    from ballista_tpu.observability import ledger as obs_ledger
    from benchmarks.tpch.schema_def import register_tpch

    qdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "benchmarks", "tpch", "queries")
    sqls = {q: open(os.path.join(qdir, f"{q}.sql")).read() for q in mix}

    # per-lane latency attribution: the LocalCluster's scheduler runs
    # in-process, so its assembled job ledgers land in THIS process's
    # ledger log — size it to hold the whole storm
    os.environ.setdefault(
        "BALLISTA_LEDGER_LOG",
        str(max(4096, 2 * sessions * queries_per_session)))
    obs_ledger.reset_process_log()

    cluster = LocalCluster(num_executors=executors,
                           concurrent_tasks=slots)
    try:
        # -- warm pass: one unloaded run of every mix query ----------------
        warm_ctx = BallistaContext.remote(
            "localhost", cluster.port,
            **{"job.timeout": str(job_timeout),
               "session.id": "serving-warmup"})
        register_tpch(warm_ctx, data_dir, "tbl")
        solo = {}
        for q in mix:
            t0 = time.time()
            warm_ctx.sql(sqls[q]).collect()
            solo[q] = round(time.time() - t0, 4)

        # -- the storm -----------------------------------------------------
        svc = cluster.service
        admitted0 = svc.admission.admitted_total
        sheds0 = svc.admission.sheds_total
        latencies: list = []
        errors: list = []
        lat_lock = threading.Lock()
        peak_queue = [0]
        stop = threading.Event()

        def watch_queue():
            while not stop.is_set():
                peak_queue[0] = max(peak_queue[0],
                                    svc.admission.queue_depth())
                time.sleep(0.05)

        watcher = threading.Thread(target=watch_queue, daemon=True)
        watcher.start()

        def run_session(idx: int):
            settings = {
                "job.timeout": str(job_timeout),
                "session.id": f"serving-{idx}",
                "admission.max_running_jobs": str(max_running),
                "admission.max_session_jobs": str(session_quota),
            }
            ctx = BallistaContext.remote("localhost", cluster.port,
                                         **settings)
            register_tpch(ctx, data_dir, "tbl")
            for j in range(queries_per_session):
                q = mix[(idx + j) % len(mix)]
                t0 = time.time()
                try:
                    ctx.sql(sqls[q]).collect()
                except AdmissionRejected as e:
                    # terminal shed (client retries exhausted): counted
                    # separately — not an engine error
                    with lat_lock:
                        errors.append((q, f"shed:{e.reason}"))
                except Exception as e:  # noqa: BLE001 - recorded
                    with lat_lock:
                        errors.append((q, f"{type(e).__name__}: {e}"))
                else:
                    with lat_lock:
                        latencies.append((q, time.time() - t0))

        threads = [threading.Thread(target=run_session, args=(i,))
                   for i in range(sessions)]
        t0 = time.time()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall = time.time() - t0
        stop.set()
        watcher.join(1)

        lats = sorted(s for _, s in latencies)
        per_query = {}
        for q, s in latencies:
            per_query.setdefault(q, []).append(s)
        # storm-window ledgers only (warmup recorded before t0): where
        # each query's wall time went, phase by phase
        ledgers = [e for e in
                   obs_ledger.process_ledger_log().entries(since=t0)
                   if e.get("origin") == "cluster"
                   and e.get("status") == "completed"]
        result = {
            "metric": "serving_qps",
            "unit": "queries/s",
            "value": round(len(lats) / wall, 3) if wall > 0 else 0.0,
            "serving_wall_seconds": round(wall, 3),
            "serving_sessions": sessions,
            "serving_queries": sessions * queries_per_session,
            "serving_completed": len(lats),
            "serving_errors": len([e for e in errors
                                   if not e[1].startswith("shed:")]),
            "serving_sheds": (svc.admission.sheds_total - sheds0),
            "serving_admitted": (svc.admission.admitted_total
                                 - admitted0),
            "serving_peak_queue_depth": peak_queue[0],
            "serving_p50_seconds": round(_percentile(lats, 0.50), 4),
            "serving_p99_seconds": round(_percentile(lats, 0.99), 4),
            "serving_max_seconds": round(lats[-1], 4) if lats else 0.0,
            "serving_solo_seconds": solo,
            "serving_query_p50": {
                q: round(_percentile(sorted(v), 0.5), 4)
                for q, v in sorted(per_query.items())},
            "serving_ledgers": len(ledgers),
            "p99_attribution": _p99_attribution(ledgers),
        }
        for phase in obs_ledger.LEDGER_PHASES:
            vals = _ledger_phase_vals(ledgers, phase)
            result[f"serving_{phase}_p50_seconds"] = round(
                _percentile(vals, 0.50), 4)
            result[f"serving_{phase}_p99_seconds"] = round(
                _percentile(vals, 0.99), 4)
        if errors:
            result["serving_error_sample"] = str(errors[:3])[:300]
        return result
    finally:
        cluster.shutdown()


def _tpch_query_params(sql: str, data_dir: str, settings: dict):
    """ExecuteQueryParams for server-side SQL planning: the raw query
    plus one catalog descriptor per TPC-H table (what submit_sql ships
    over the wire, built directly for in-process service calls)."""
    from ballista_tpu import serde
    from ballista_tpu.io import TblSource
    from ballista_tpu.proto import ballista_pb2 as pb
    from benchmarks.tpch.schema_def import TPCH_PKS, TPCH_SCHEMAS

    params = pb.ExecuteQueryParams()
    params.sql = sql
    for k, v in settings.items():
        params.settings[k] = v
    for name, sch in TPCH_SCHEMAS.items():
        path = os.path.join(data_dir, name)
        if not os.path.exists(path):
            path = os.path.join(data_dir, f"{name}.tbl")
        entry = params.catalog.add()
        entry.name = name
        entry.source.CopyFrom(
            serde.source_to_proto(TblSource(path, sch), TPCH_PKS[name]))
    return params


def run_restart(data_dir: str, jobs: int = 6, mix=QUERY_MIX,
                job_timeout: float = 600.0) -> dict:
    """The restart phase: submit a mixed batch against a sqlite-backed
    scheduler (admission.max_running_jobs=1 makes one job admit + plan
    while the rest queue), abandon the service without any shutdown,
    rebuild it over the same file and time the recovery pass — the
    serving gap a real restart would cost."""
    import shutil
    import tempfile

    from ballista_tpu.distributed.scheduler import SchedulerService
    from ballista_tpu.distributed.state import (SchedulerState,
                                                SqliteBackend)

    qdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "benchmarks", "tpch", "queries")
    sqls = {q: open(os.path.join(qdir, f"{q}.sql")).read() for q in mix}
    tmp = tempfile.mkdtemp(prefix="ballista-restart-bench-")
    db = os.path.join(tmp, "state.db")
    try:
        svc = SchedulerService(SchedulerState(SqliteBackend(db)))
        settings = {
            "session.id": "restart-bench",
            "admission.max_running_jobs": "1",
            "admission.queue_timeout_secs": str(job_timeout),
        }
        job_ids = []
        for j in range(jobs):
            r = svc.ExecuteQuery(_tpch_query_params(
                sqls[mix[j % len(mix)]], data_dir, settings))
            job_ids.append(r.job_id)
        deadline = time.time() + job_timeout
        while not svc.journal.is_planned(job_ids[0]):
            if time.time() > deadline:
                raise RuntimeError("first job never finished planning")
            time.sleep(0.01)
        svc.close_health()  # abandon in place: the "crash"

        t0 = time.time()
        svc2 = SchedulerService(SchedulerState(SqliteBackend(db)))
        report = svc2.recover()
        recovery_wall = time.time() - t0  # rehydrate + recovery pass
        svc2.close_health()
        return {
            "metric": "recovered_jobs",
            "unit": "jobs",
            "value": report.recovered_jobs,
            "recovery_seconds": round(recovery_wall, 4),
            "recovery_pass_seconds": report.recovery_seconds,
            "recovery_inflight": report.jobs_inflight,
            "recovery_queued_restored": report.queued_restored,
            "recovery_relaunched": report.relaunched,
            "recovery_tasks_requeued": report.tasks_requeued,
            "recovery_orphans_failed": report.orphans_failed,
            "recovery_errors": len(report.errors),
            "restart_jobs_submitted": jobs,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def run_autoscale(data_dir: str, sessions: int = 4,
                  queries_per_session: int = 6, executors: int = 2,
                  slots: int = 2, job_timeout: float = 600.0,
                  mix=QUERY_MIX) -> dict:
    """The autoscale phase: a 2x session burst against a MIN-sized
    fleet with the autoscaler on — it must grow toward the max bound
    and keep the burst's tail latency finite, then drain back once
    idle. Decisions land in system.autoscaler."""
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.distributed.controlplane import AutoscalerConfig
    from ballista_tpu.distributed.executor import LocalCluster
    from benchmarks.tpch.schema_def import register_tpch

    qdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "benchmarks", "tpch", "queries")
    sqls = {q: open(os.path.join(qdir, f"{q}.sql")).read() for q in mix}
    burst_sessions = 2 * sessions

    cluster = LocalCluster(num_executors=1, concurrent_tasks=slots)
    try:
        svc = cluster.service
        svc.attach_autoscaler(
            AutoscalerConfig(enabled=True, min_executors=1,
                             max_executors=executors, backlog_tasks=2,
                             cooldown_secs=1.0, idle_secs=2.0,
                             interval_secs=0.25),
            spawn_fn=cluster.add_executor,
            drain_fn=cluster.remove_executor)

        warm_ctx = BallistaContext.remote(
            "localhost", cluster.port,
            **{"job.timeout": str(job_timeout),
               "session.id": "autoscale-warmup"})
        register_tpch(warm_ctx, data_dir, "tbl")
        for q in mix:
            warm_ctx.sql(sqls[q]).collect()

        latencies: list = []
        errors: list = []
        lat_lock = threading.Lock()
        peak_executors = [1]
        stop = threading.Event()

        def watch_fleet():
            while not stop.is_set():
                peak_executors[0] = max(peak_executors[0],
                                        len(cluster.executors))
                time.sleep(0.05)

        watcher = threading.Thread(target=watch_fleet, daemon=True)
        watcher.start()

        def run_session(idx: int):
            ctx = BallistaContext.remote(
                "localhost", cluster.port,
                **{"job.timeout": str(job_timeout),
                   "session.id": f"autoscale-{idx}"})
            register_tpch(ctx, data_dir, "tbl")
            for j in range(queries_per_session):
                q = mix[(idx + j) % len(mix)]
                t0 = time.time()
                try:
                    ctx.sql(sqls[q]).collect()
                except Exception as e:  # noqa: BLE001 - recorded
                    with lat_lock:
                        errors.append((q, f"{type(e).__name__}: {e}"))
                else:
                    with lat_lock:
                        latencies.append(time.time() - t0)

        threads = [threading.Thread(target=run_session, args=(i,))
                   for i in range(burst_sessions)]
        t0 = time.time()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall = time.time() - t0
        stop.set()
        watcher.join(1)

        scaler = svc.autoscaler
        lats = sorted(latencies)
        return {
            "metric": "autoscale_qps",
            "unit": "queries/s",
            "value": round(len(lats) / wall, 3) if wall > 0 else 0.0,
            "autoscale_wall_seconds": round(wall, 3),
            "autoscale_sessions": burst_sessions,
            "autoscale_completed": len(lats),
            "autoscale_errors": len(errors),
            "autoscale_events": (scaler.scale_ups_total
                                 + scaler.scale_downs_total),
            "autoscale_ups": scaler.scale_ups_total,
            "autoscale_downs": scaler.scale_downs_total,
            "autoscale_peak_executors": peak_executors[0],
            "autoscale_max_executors": executors,
            "autoscale_p50_seconds": round(_percentile(lats, 0.50), 4),
            "autoscale_p99_seconds": round(_percentile(lats, 0.99), 4),
            "autoscale_error_sample": (str(errors[:3])[:300]
                                       if errors else ""),
        }
    finally:
        cluster.shutdown()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--phase", choices=("serving", "restart",
                                        "autoscale"),
                    default="serving")
    ap.add_argument("--scale", type=float, default=0.05)
    ap.add_argument("--data", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks",
        "data_serving"))
    ap.add_argument("--sessions", type=int, default=4)
    ap.add_argument("--queries-per-session", type=int, default=6)
    ap.add_argument("--executors", type=int, default=2)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--max-running", type=int, default=4)
    ap.add_argument("--session-quota", type=int, default=2)
    args = ap.parse_args()

    from benchmarks.tpch import datagen

    data_dir = os.path.join(args.data, f"sf{args.scale}")
    marker = os.path.join(data_dir, ".complete")
    if not os.path.exists(marker):
        print(f"# generating TPC-H SF{args.scale} into {data_dir}",
              file=sys.stderr)
        datagen.generate(data_dir, scale=args.scale, num_parts=2)
        open(marker, "w").write("ok\n")

    if args.phase == "restart":
        result = run_restart(
            data_dir, jobs=args.sessions * 2)
    elif args.phase == "autoscale":
        result = run_autoscale(
            data_dir, sessions=args.sessions,
            queries_per_session=args.queries_per_session,
            executors=args.executors, slots=args.slots)
    else:
        result = run_serving(
            data_dir, sessions=args.sessions,
            queries_per_session=args.queries_per_session,
            executors=args.executors, slots=args.slots,
            max_running=args.max_running,
            session_quota=args.session_quota)
    # warm-path cache effectiveness rides along on every line: a
    # serving deployment that never hits its caches is leaving the
    # memory-speed path on the table (docs/caching.md)
    from ballista_tpu.cache import cache_counters
    cc = cache_counters()
    result["table_cache_hits"] = int(cc["table_cache_hits"])
    result["result_cache_hits"] = int(cc["result_cache_hits"])
    result["donated_buffers"] = int(cc["donated_buffers"])
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
